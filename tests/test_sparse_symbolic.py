"""Symbolic sparse execution (VERDICT r1 #6).

Storage-type inference over Symbol graphs + the flagship sparse path:
Embedding(sparse_grad=True) produces RowSparseNDArray weight gradients
through the symbolic executor and Module.fit — the dense (vocab, dim)
gradient is never materialized — and the update stays sparse through the
optimizer's lazy row update and the kvstore's server-side-optimizer
analog. CSR inputs flow through jitted graphs as BCOO (dot never
densifies). Reference: infer_graph_attr_pass.cc:356,
attach_op_execs_pass.cc:47-200, the sparse embedding FComputeEx path.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu.ndarray.sparse import RowSparseNDArray


def _embed_net(V, D, C):
    data = mx.sym.var("data")
    w = mx.sym.var("embed_weight", stype="row_sparse")
    emb = mx.sym.Embedding(data, w, input_dim=V, output_dim=D,
                           sparse_grad=True, name="embed")
    fc = mx.sym.FullyConnected(emb, num_hidden=C, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def test_embedding_sparse_grad_rows_and_values():
    V, D, B = 1000, 16, 8
    data = mx.sym.var("data")
    w = mx.sym.var("embed_weight", stype="row_sparse")
    emb = mx.sym.Embedding(data, w, input_dim=V, output_dim=D,
                           sparse_grad=True, name="embed")
    out = mx.sym.sum(emb)
    ex = out.simple_bind(mx.cpu(), data=(B,),
                         grad_req={"embed_weight": "write", "data": "null"})
    ex.arg_dict["embed_weight"][:] = mx.nd.array(
        np.random.RandomState(0).randn(V, D).astype(np.float32))
    idx = np.array([3, 5, 3, 999, 0, 5, 5, 42], np.float32)
    ex.forward(is_train=True, data=idx)
    ex.backward()
    g = ex.grad_dict["embed_weight"]
    assert isinstance(g, RowSparseNDArray)      # never densified
    assert g.data.shape == (5, D)               # unique rows only
    assert list(g.indices.asnumpy()) == [0, 3, 5, 42, 999]
    counts = {0: 1, 3: 2, 5: 3, 42: 1, 999: 1}
    for r, v in zip(g.indices.asnumpy(), g.data.asnumpy()):
        np.testing.assert_allclose(v, counts[int(r)] * np.ones(D),
                                   rtol=1e-6)


def test_sparse_grad_matches_dense_grad():
    """The rsp grad, densified, must equal the ordinary dense grad."""
    V, D, C, B = 50, 8, 4, 16
    rng = np.random.RandomState(1)
    idx = rng.randint(0, V, (B,)).astype(np.float32)
    lab = rng.randint(0, C, (B,)).astype(np.float32)
    W = rng.randn(V, D).astype(np.float32)
    fcw = rng.randn(C, D).astype(np.float32)
    grads = {}
    for sparse in (True, False):
        data = mx.sym.var("data")
        w = mx.sym.var("embed_weight")
        emb = mx.sym.Embedding(data, w, input_dim=V, output_dim=D,
                               sparse_grad=sparse, name="embed")
        fc = mx.sym.FullyConnected(emb, num_hidden=C, name="fc")
        net = mx.sym.SoftmaxOutput(fc, name="softmax")
        ex = net.simple_bind(mx.cpu(), data=(B,), softmax_label=(B,),
                             grad_req={"embed_weight": "write",
                                       "fc_weight": "write",
                                       "fc_bias": "null", "data": "null",
                                       "softmax_label": "null"})
        ex.arg_dict["embed_weight"][:] = mx.nd.array(W)
        ex.arg_dict["fc_weight"][:] = mx.nd.array(fcw)
        ex.forward(is_train=True, data=idx, softmax_label=lab)
        ex.backward()
        g = ex.grad_dict["embed_weight"]
        grads[sparse] = (g.todense().asnumpy()
                         if isinstance(g, RowSparseNDArray) else g.asnumpy())
        if sparse:
            assert isinstance(g, RowSparseNDArray)
            assert g.data.shape[0] == len(np.unique(idx))
    np.testing.assert_allclose(grads[True], grads[False],
                               rtol=1e-4, atol=1e-5)


def test_module_fit_sparse_embedding_stays_sparse():
    """Flagship: Module.fit on an embedding classifier; every step's
    weight grad is row_sparse and training converges."""
    from mxnet_tpu.io import NDArrayIter

    V, D, C, B = 200, 16, 4, 32
    rng = np.random.RandomState(2)
    tokens = rng.randint(0, V, (256,)).astype(np.float32)
    labels = (tokens.astype(int) % C).astype(np.float32)
    it = NDArrayIter(tokens, labels, batch_size=B, shuffle=False,
                     label_name="softmax_label")
    mod = mx.mod.Module(_embed_net(V, D, C), data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 1.0},
            initializer=mx.init.Xavier(),
            eval_metric="acc")
    g = mod._exec.grad_dict["embed_weight"]
    assert isinstance(g, RowSparseNDArray)
    assert g.data.shape[0] <= B < V    # rows bounded by batch, not vocab
    score = mod.score(it, mx.metric.Accuracy())
    it.reset()
    assert dict(score)["accuracy"] > 0.95


def test_update_on_kvstore_row_sparse():
    """The server-side-optimizer analog with rsp grads: push a row_sparse
    gradient, let the store-side updater apply it lazily, row_sparse_pull
    only the touched rows."""
    V, D = 100, 8
    kv = mx.kv.create("local")
    opt = mx.optimizer.SGD(learning_rate=0.5, rescale_grad=1.0)
    kv.set_optimizer(opt)
    w0 = np.ones((V, D), np.float32)
    kv.init("w", mx.nd.array(w0))
    rows = np.array([3, 7], np.int64)
    vals = np.full((2, D), 2.0, np.float32)
    kv.push("w", sp.RowSparseNDArray(vals, rows, (V, D)))
    out = mx.nd.zeros((V, D))
    kv.pull("w", out=out)
    got = out.asnumpy()
    expect = w0.copy()
    expect[rows] -= 0.5 * 2.0       # only touched rows updated
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    # row_sparse_pull fills just the requested rows
    pulled = sp.zeros("row_sparse", (V, D))
    kv.row_sparse_pull("w", out=pulled, row_ids=mx.nd.array([3.0, 50.0]))
    assert isinstance(pulled, RowSparseNDArray)
    assert pulled.data.shape[0] == 2            # only the asked-for rows
    np.testing.assert_allclose(pulled.todense().asnumpy()[3],
                               expect[3], rtol=1e-5)


def test_infer_storage_type_rules():
    x = mx.sym.var("x", stype="csr")
    w = mx.sym.var("w")
    y = mx.sym.dot(x, w)
    arg_st, out_st, _ = y.infer_storage_type()
    assert dict(zip(y.list_arguments(), arg_st)) == {"x": "csr",
                                                     "w": "default"}
    assert out_st == ["default"]        # dot(csr, dense) -> dense out

    a = mx.sym.var("a", stype="row_sparse")
    b = mx.sym.var("b", stype="row_sparse")
    s = mx.sym.elemwise_add(a, b)
    assert s.infer_storage_type()[1] == ["row_sparse"]
    # dense fallback: rsp through an un-ruled op densifies
    t = mx.sym.Activation(a, act_type="relu")
    assert t.infer_storage_type()[1] == ["default"]
    c = mx.sym.cast_storage(mx.sym.var("d"), stype="csr")
    assert c.infer_storage_type()[1] == ["csr"]


def test_symbolic_csr_dot_never_densifies():
    x = mx.sym.var("x", stype="csr")
    w = mx.sym.var("w")
    y = mx.sym.dot(x, w)
    ex = y.simple_bind(mx.cpu(), x=(4, 6), w=(6, 3), grad_req="null")
    dense = np.zeros((4, 6), np.float32)
    dense[0, 1], dense[2, 4] = 2.0, 3.0
    ex.arg_dict["x"] = sp.csr_matrix(dense)
    wv = np.random.RandomState(3).randn(6, 3).astype(np.float32)
    ex.arg_dict["w"][:] = mx.nd.array(wv)
    out = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, dense @ wv, rtol=1e-5)


def test_sparse_grad_add_req_rejected():
    net = _embed_net(50, 8, 4)
    with pytest.raises(mx.MXNetError):
        net.simple_bind(mx.cpu(), data=(4,), softmax_label=(4,),
                        grad_req={"embed_weight": "add", "fc_weight": "write",
                                  "fc_bias": "write", "data": "null",
                                  "softmax_label": "null"})


def test_reshape_executor_backward_works():
    # regression: reshaped (shared_exec) executors must keep working
    # through backward, including when the symbol has no sparse nodes
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3, name="fc"),
        name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(4, 6), softmax_label=(4,),
                         grad_req="write")
    ex2 = ex.reshape(data=(8, 6), softmax_label=(8,))
    rng = np.random.RandomState(0)
    ex2.forward(is_train=True, data=rng.rand(8, 6).astype(np.float32),
                softmax_label=np.zeros(8, np.float32))
    ex2.backward()
    assert np.isfinite(ex2.grad_dict["fc_weight"].asnumpy()).all()


def test_tied_sparse_embedding_falls_back_dense():
    # weight consumed twice (embedding + tied lm head): sparse-grad path
    # must fall back to the always-correct dense gradient
    V, D, B = 30, 8, 4
    data = mx.sym.var("data")
    w = mx.sym.var("embed_weight")
    emb = mx.sym.Embedding(data, w, input_dim=V, output_dim=D,
                           sparse_grad=True, name="embed")
    pooled = mx.sym.mean(emb, axis=(1,)) if False else emb
    logits = mx.sym.dot(pooled, w, transpose_b=True)
    out = mx.sym.sum(logits)
    ex = out.simple_bind(mx.cpu(), data=(B,),
                         grad_req={"embed_weight": "write", "data": "null"})
    rng = np.random.RandomState(0)
    W = rng.randn(V, D).astype(np.float32)
    ex.arg_dict["embed_weight"][:] = mx.nd.array(W)
    idx = np.array([1, 2, 1, 5], np.float32)
    ex.forward(is_train=True, data=idx)
    ex.backward()
    g = ex.grad_dict["embed_weight"]
    assert not isinstance(g, RowSparseNDArray)   # dense fallback
    # numeric check vs autodiff-free formula: out = sum(E @ W^T),
    # dE = sum_cols(W) rows scattered; dW via both paths
    import jax.numpy as jnp
    def f(Wj):
        E = jnp.take(Wj, jnp.asarray(idx, jnp.int32), axis=0)
        return jnp.sum(E @ Wj.T)
    import jax
    expect = jax.grad(f)(jnp.asarray(W))
    np.testing.assert_allclose(g.asnumpy(), np.asarray(expect),
                               rtol=1e-4, atol=1e-5)


def test_sparse_grad_writes_through_bound_arrays():
    """bind(args_grad=...) contract: the gradient lands IN the arrays the
    caller supplied (reference: GraphExecutor writes grads into the bound
    NDArrays; C-ABI callers read them via the handle they passed in).
    A bound rsp array is updated in place; a bound dense array receives
    the scattered rows."""
    V, D, B = 40, 4, 6
    data = mx.sym.var("data")
    w = mx.sym.var("embed_weight", stype="row_sparse")
    emb = mx.sym.Embedding(data, w, input_dim=V, output_dim=D,
                           sparse_grad=True, name="embed")
    out = mx.sym.sum(emb)
    rng = np.random.RandomState(3)
    W = rng.randn(V, D).astype(np.float32)
    idx = np.array([7, 2, 7, 11, 0, 2], np.float32)

    # caller-bound row_sparse gradient array: same object, new contents
    g_rsp = RowSparseNDArray(np.zeros((0, D), np.float32),
                             np.zeros((0,), np.int32), (V, D))
    ex = out.bind(mx.cpu(),
                  args={"data": mx.nd.array(idx),
                        "embed_weight": mx.nd.array(W)},
                  args_grad={"embed_weight": g_rsp},
                  grad_req={"embed_weight": "write", "data": "null"})
    _ = g_rsp.asnumpy()        # populate the cached dense view pre-backward
    ex.forward(is_train=True)
    ex.backward()
    assert ex.grad_dict["embed_weight"] is g_rsp
    assert list(g_rsp.indices.asnumpy()) == [0, 2, 7, 11]
    # the in-place component swap must invalidate the cached dense view
    dense_after = g_rsp.asnumpy()
    expect_rsp = np.zeros((V, D), np.float32)
    np.add.at(expect_rsp, idx.astype(np.int64), np.ones((B, D), np.float32))
    np.testing.assert_allclose(dense_after, expect_rsp, rtol=1e-6)

    # caller-bound dense gradient array: written through, not rebound
    g_dense = mx.nd.zeros((V, D))
    ex2 = out.bind(mx.cpu(),
                   args={"data": mx.nd.array(idx),
                         "embed_weight": mx.nd.array(W)},
                   args_grad={"embed_weight": g_dense},
                   grad_req={"embed_weight": "write", "data": "null"})
    ex2.forward(is_train=True)
    ex2.backward()
    assert ex2.grad_dict["embed_weight"] is g_dense
    expect = np.zeros((V, D), np.float32)
    np.add.at(expect, idx.astype(np.int64), np.ones((B, D), np.float32))
    np.testing.assert_allclose(g_dense.asnumpy(), expect, rtol=1e-6)
