"""Silent-failure integrity guard (resilience/integrity.py,
docs/how_to/integrity.md).

The lying chip on the virtual 8-device CPU mesh: a seeded FaultPlan
fires ``mesh.silent_corrupt`` to flip one low mantissa bit in one
device's copy of one parameter shard — every health probe keeps
passing, nothing raises, and only the cross-replica checksum vote can
see it. The vote must localize exactly the injected device, quarantine
it through MeshHealth, and the elastic controller must re-mesh and
resume with the bitwise-identical batch stream and allclose losses
versus an uninterrupted run. The in-trace divergence sentinel rides the
donated step state (zero per-step host syncs) and drives the
rollback-and-replay ladder: transient breaches vanish on replay, poison
batches breach twice at the same position and are quarantined under the
data-guard budget. ``integrity.checksum`` fails the vote itself — that
must propagate, never read as clean. All clocks injectable, zero real
sleeps (the chaos smoke ``ci/integrity_smoke.py`` runs the same
contract under ``MXNET_TPU_FAULT_PLAN``).
"""
import hashlib
import itertools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models, resilience
from mxnet_tpu.parallel import SPMDTrainer, make_mesh
from mxnet_tpu.resilience import FaultPlan, faults
from mxnet_tpu.resilience import integrity as ig_mod
from mxnet_tpu.resilience.data import DataBudgetExceeded
from mxnet_tpu.resilience.elastic import ElasticConfig, MeshHealth
from mxnet_tpu.resilience.integrity import (ChecksumMismatch,
                                            DivergenceDetected,
                                            IntegrityConfig,
                                            IntegrityGuard,
                                            init_sentinel,
                                            resolve_config,
                                            sentinel_stats,
                                            update_sentinel)

BATCH = 16


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    resilience.reset_stats()
    ig_mod._last_injected = None
    yield
    faults.disarm()
    resilience.reset_stats()


def _make_trainer(mesh_axes=None, devices=None, batch=BATCH,
                  integrity=None):
    mesh = make_mesh(mesh_axes or {"data": 8}, devices=devices)
    s = models.get_symbol("mlp", num_classes=10)
    tr = SPMDTrainer(
        s, optimizer="sgd",
        optimizer_params=dict(learning_rate=0.1, momentum=0.9,
                              rescale_grad=1.0 / batch),
        mesh=mesh, integrity=integrity)
    mx.random.seed(42)
    tr.bind(data_shapes={"data": (batch, 784)},
            label_shapes={"softmax_label": (batch,)})
    return tr


def _feed(seed=0, batch=BATCH):
    rng = np.random.RandomState(seed)
    return {"data": rng.randn(batch, 784).astype(np.float32),
            "softmax_label": rng.randint(0, 10, (batch,))
            .astype(np.float32)}


def _tonp(v):
    return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)


# ---------------------------------------------------------------------------
# the in-trace divergence sentinel (unit: scripted gradient streams)
# ---------------------------------------------------------------------------

def _norm_grads(value):
    """A one-leaf grad pytree whose global norm is exactly ``value``."""
    return {"w": jnp.full((4,), np.float32(value) / 2.0)}


def _run_stream(cfg, values, applied=None):
    state = tuple(jnp.asarray(x) for x in init_sentinel())
    for t, v in enumerate(values, start=1):
        a = None if applied is None else applied[t - 1]
        state = update_sentinel(cfg, state, _norm_grads(v), t,
                                applied=None if a is None
                                else jnp.bool_(a))
    return sentinel_stats(state)


def test_sentinel_quiet_on_noisy_but_healthy_stream():
    """True negative: 60 samples of ordinary gradient-norm noise never
    breach — a healthy run pays zero host syncs AND zero false alarms."""
    rng = np.random.RandomState(3)
    st = _run_stream(IntegrityConfig(zmax=6.0, warmup=8),
                     1.0 + 0.05 * rng.randn(60))
    assert st["flag"] == 0
    assert st["samples"] == 60
    assert abs(st["mean"] - 1.0) < 0.05


def test_sentinel_z_breach_is_sticky_and_not_folded():
    """True positive: a 100x spike after warmup breaches the z tier,
    stamps the FIRST breaching update, and is never folded into the
    running statistics (folding first would cap z at ~sqrt(n) and blind
    the test to exactly these spikes)."""
    rng = np.random.RandomState(4)
    vals = list(1.0 + 0.05 * rng.randn(20)) + [100.0] + \
        list(1.0 + 0.05 * rng.randn(5))
    st = _run_stream(IntegrityConfig(zmax=6.0, warmup=8), vals)
    assert st["flag"] == 1                   # z-score code
    assert st["breach_step"] == 21           # first breach stamped
    assert st["samples"] == 25               # spike not folded
    assert abs(st["mean"] - 1.0) < 0.05      # stats uncontaminated


def test_sentinel_abs_tier_needs_no_warmup():
    """Non-finite (or over grad_max) is a breach on sample one — no
    statistics needed."""
    st = _run_stream(IntegrityConfig(zmax=6.0, warmup=8), [np.nan])
    assert st["flag"] == 2 and st["breach_step"] == 1
    st = _run_stream(IntegrityConfig(grad_max=10.0, warmup=8),
                     [1.0, 50.0])
    assert st["flag"] == 2 and st["breach_step"] == 2
    assert st["samples"] == 1


def test_sentinel_loss_scale_skip_is_neither_breach_nor_sample():
    """A step the loss-scale guard skipped (applied=False) is the
    scale schedule's business: not an integrity breach, not a
    statistics sample."""
    st = _run_stream(IntegrityConfig(zmax=6.0, warmup=2),
                     [1.0, 1.0, np.nan, 1.0],
                     applied=[True, True, False, True])
    assert st["flag"] == 0
    assert st["samples"] == 3


def test_resolve_config_env_and_explicit(monkeypatch):
    monkeypatch.delenv("MXTPU_INTEGRITY_PERIOD", raising=False)
    assert resolve_config(None) is None          # default: disabled
    assert resolve_config(False) is None
    assert resolve_config(True).period == 1      # forced on
    assert resolve_config(IntegrityConfig(period=0)) is None
    monkeypatch.setenv("MXTPU_INTEGRITY_PERIOD", "5")
    monkeypatch.setenv("MXTPU_INTEGRITY_ZMAX", "4.5")
    monkeypatch.setenv("MXTPU_INTEGRITY_WARMUP", "3")
    cfg = resolve_config(None)
    assert (cfg.period, cfg.zmax, cfg.warmup) == (5, 4.5, 3)
    assert cfg.grad_max is None
    # zmax/grad_max/warmup are traced constants: they key the program
    assert cfg.signature() != IntegrityConfig().signature()


def test_period_zero_is_bitwise_disable():
    """MXTPU_INTEGRITY_PERIOD=0 (the default): no sentinel state enters
    the donated step, no extra outputs, no stats surface — and the
    trained parameters are bitwise-identical to an armed run's (the
    sentinel only OBSERVES; only its absence must also be free)."""
    tr_off = _make_trainer()
    assert tr_off._ig_cfg is None and tr_off._ig_state is None
    assert tr_off.integrity_stats() is None
    tr_on = _make_trainer(integrity=IntegrityConfig(period=1))
    assert tr_on.integrity_stats() is not None
    for i in range(3):
        tr_off.step(_feed(i))
        tr_on.step(_feed(i))
    for n in tr_off.params:
        np.testing.assert_array_equal(np.asarray(tr_off.params[n]),
                                      np.asarray(tr_on.params[n]),
                                      err_msg=n)
    assert tr_on.integrity_stats()["samples"] == 3
    assert tr_off.retrace_guard.count == 1     # one compile each, no
    assert tr_on.retrace_guard.count == 1      # retrace from the carry


# ---------------------------------------------------------------------------
# the lying chip: seeded bitflip + cross-replica checksum vote
# ---------------------------------------------------------------------------

def test_bitflip_is_seed_deterministic_and_sentinel_invisible():
    """The same armed plan flips the same bit on the same device every
    run (the chaos smoke replays corruption byte-for-byte), and a low
    mantissa bit stays finite — invisible to the divergence sentinel by
    construction, detectable only bitwise."""
    victims = []
    for _ in range(2):
        tr = _make_trainer(integrity=IntegrityConfig(period=1))
        tr.step(_feed(0))
        before = {n: np.asarray(v).copy() for n, v in tr.params.items()}
        faults.arm(FaultPlan(seed=11).arm("mesh.silent_corrupt", nth=1))
        tr.step(_feed(1))
        faults.disarm()
        inj = ig_mod._last_injected
        assert inj is not None
        victims.append((inj["device"], inj["param"], inj["word"],
                        inj["bit"]))
        # exactly one param changed beyond the step's own update, and
        # the corrupted copy is still finite
        assert np.isfinite(np.asarray(tr.params[inj["param"]])).all()
        assert tr.integrity_stats()["flag"] == 0
        del before
    assert victims[0] == victims[1]


def test_checksum_vote_localizes_exactly_the_injected_device():
    tr = _make_trainer(integrity=IntegrityConfig(period=1))
    tr.step(_feed(0))
    guard = IntegrityGuard(tr, tr._ig_cfg)
    assert guard.checksum_round() == ("ok", None)   # clean vote
    faults.arm(FaultPlan(seed=7).arm("mesh.silent_corrupt", nth=1))
    tr.step(_feed(1))
    faults.disarm()
    verdict, device_id = guard.checksum_round()
    assert verdict == "mismatch"
    assert device_id == ig_mod._last_injected["device"]
    st = resilience.stats()["integrity"]
    assert st["checksum_rounds"] == 2 and st["votes"] > 0


def test_check_now_marks_device_through_shared_mesh_health():
    """The vote-localized chip is quarantined through the SAME
    MeshHealth exclusion path a probed loss takes, and the raised
    ChecksumMismatch says so (already_marked) — the controller must not
    layer a seeded guess on top."""
    tr = _make_trainer(integrity=IntegrityConfig(period=1))
    tr.step(_feed(0))
    health = MeshHealth()
    guard = IntegrityGuard(tr, tr._ig_cfg, health=health)
    guard.check_now()                       # clean round: no breach
    assert guard.gate() is True
    faults.arm(FaultPlan(seed=7).arm("mesh.silent_corrupt", nth=1))
    tr.step(_feed(1))
    faults.disarm()
    with pytest.raises(ChecksumMismatch) as exc:
        guard.check_now()
    assert exc.value.already_marked is True
    assert exc.value.device_id == ig_mod._last_injected["device"]
    assert guard.gate() is False            # breached: commits refused
    healthy = {d.id for d in health.healthy_devices()}
    assert exc.value.device_id not in healthy
    assert resilience.stats()["integrity"]["quarantines"] == 1


def test_checksum_fault_site_propagates_never_reads_clean():
    """integrity.checksum fails the vote INFRASTRUCTURE: that must
    surface, never be mistaken for a clean round."""
    tr = _make_trainer(integrity=IntegrityConfig(period=1))
    tr.step(_feed(0))
    guard = IntegrityGuard(tr, tr._ig_cfg)
    faults.arm(FaultPlan(seed=0).arm("integrity.checksum", nth=1,
                                     exc="ioerror"))
    with pytest.raises(faults.InjectedFault):
        guard.check_now()
    faults.disarm()
    assert resilience.stats()["integrity"]["checksum_rounds"] == 0


# ---------------------------------------------------------------------------
# rollback window: contamination pruning + MXTPU_CKPT_KEEP retention
# ---------------------------------------------------------------------------

def test_prune_rolls_back_past_two_contaminated_stems(tmp_path):
    """A divergence detected N steps late has been checkpointing corrupt
    state the whole window: every save newer than the last validated
    update must be pruned, and the MXTPU_CKPT_KEEP window must have kept
    an older one to land on."""
    tr = _make_trainer(integrity=IntegrityConfig(period=1))
    for i in range(4):
        tr.step(_feed(i))
        tr.save_checkpoint(str(tmp_path), step=tr._num_update, epoch=0)
    guard = IntegrityGuard(tr, tr._ig_cfg, checkpoint_dir=str(tmp_path))
    guard._last_good_update = 2     # updates 3 and 4 are suspect
    guard._prune_contaminated()
    left = sorted(n for n in os.listdir(str(tmp_path))
                  if n.startswith("step_"))
    assert left == ["step_1", "step_2"]
    # the rollback rung lands on the newest SURVIVING stem
    assert tr.restore_latest(str(tmp_path)) is not None
    assert tr._num_update == 2


def test_ckpt_keep_window_retains_k_midepoch_stems(tmp_path, monkeypatch):
    """MXTPU_CKPT_KEEP widens the mid-epoch roll from keep-1 to
    keep-last-K, so the integrity rollback always has somewhere older to
    land."""
    monkeypatch.setenv("MXTPU_CKPT_KEEP", "3")
    tr = _make_trainer()
    X = np.random.RandomState(1).randn(96, 784).astype(np.float32)
    y = np.random.RandomState(2).randint(0, 10, (96,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    tr.fit(it, num_epoch=1, checkpoint_dir=str(tmp_path),
           checkpoint_batch_period=1)
    steps = sorted(int(n.split("_")[1]) for n in os.listdir(str(tmp_path))
                   if n.startswith("step_") and "." not in n)
    # 6 updates: the keep-3 window retains the newest three (the epoch
    # promotion reuses step_6, protected from the roll)
    assert steps == [4, 5, 6]


# ---------------------------------------------------------------------------
# chaos acceptance: detect -> localize -> quarantine -> re-mesh -> resume
# ---------------------------------------------------------------------------

def _run_fit(ckdir=None, num_epoch=3, plan=None, elastic=False,
             integrity=None, nan_batch=None, data_policy=None,
             flag_poison_at=None):
    """One fit over a fixed 48-sample set: returns (trainer, hashes,
    losses) keyed by (epoch, nbatch) — last write wins, because a
    contaminated attempt completes (and may record) before the guard
    rolls it back and the batch replays."""
    faults.disarm()
    resilience.reset_stats()
    X = np.random.RandomState(1).randn(48, 784).astype(np.float32)
    y = np.random.RandomState(2).randint(0, 10, (48,)).astype(np.float32)
    if nan_batch is not None:
        X[nan_batch * BATCH:(nan_batch + 1) * BATCH] = np.nan
    tr = _make_trainer(integrity=integrity)
    # a poisoned batch must STAY one batch: shuffling would smear the
    # NaN rows over the whole set
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH,
                           shuffle=nan_batch is None, seed=5)
    hashes, losses = {}, {}

    def record(param):
        inp = param.locals["inputs"]
        h = hashlib.sha256()
        for n in sorted(inp):
            h.update(np.ascontiguousarray(_tonp(inp[n])).tobytes())
        hashes[(param.epoch, param.nbatch)] = h.hexdigest()
        p = np.asarray(param.locals["step_outs"][0])
        lab = _tonp(inp["softmax_label"]).astype(int)
        losses[(param.epoch, param.nbatch)] = float(
            -np.log(p[np.arange(len(lab)), lab] + 1e-9).mean())
        if flag_poison_at is not None \
                and (param.epoch, param.nbatch) == flag_poison_at:
            # a simulated hardware transient: flip the device-side
            # breach flag once; the next fold keeps it sticky and the
            # guard trips at the next period boundary. The replay after
            # rollback is clean — transient, not poison.
            from jax.sharding import NamedSharding, PartitionSpec
            st = list(tr._ig_state)
            st[3] = jax.device_put(
                np.float32(2.0), NamedSharding(tr._mesh, PartitionSpec()))
            tr._ig_state = tuple(st)

    if plan is not None:
        faults.arm(plan)
    kwargs = {}
    if elastic:
        fake_clock = itertools.count()
        kwargs = dict(elastic=True, elastic_config=ElasticConfig(
            clock=lambda: float(next(fake_clock))))
    tr.fit(it, num_epoch=num_epoch,
           checkpoint_dir=str(ckdir) if ckdir else None,
           checkpoint_batch_period=1 if ckdir else None,
           batch_end_callback=record, **kwargs)
    faults.disarm()
    return tr, hashes, losses


def _assert_same_stream(got_h, got_l, ref_h, ref_l, skip=()):
    keys = set(ref_h) - set(skip)
    assert keys <= set(got_h)
    for k in sorted(keys):
        assert got_h[k] == ref_h[k], k      # bitwise batch stream
    np.testing.assert_allclose([got_l[k] for k in sorted(keys)],
                               [ref_l[k] for k in sorted(keys)],
                               rtol=1e-4, atol=1e-5)


def test_silent_corruption_votes_out_chip_and_resumes_exactly(tmp_path):
    """The headline contract: a seeded bitflip on 1 of 8 devices is
    detected within one integrity period, the vote names exactly the
    injected device, MeshHealth quarantines it, the elastic controller
    re-meshes onto survivors, and the run resumes with the bitwise batch
    stream and allclose losses/params of an uninterrupted run."""
    tr_ref, h_ref, l_ref = _run_fit(num_epoch=3)
    plan = FaultPlan(seed=7).arm("mesh.silent_corrupt", nth=4)
    tr, h, l = _run_fit(ckdir=tmp_path, num_epoch=3, plan=plan,
                        elastic=True,
                        integrity=IntegrityConfig(period=1))
    inj = ig_mod._last_injected
    assert inj is not None
    st = resilience.stats()["integrity"]
    est = resilience.stats()["elastic"]
    assert st["quarantines"] == 1           # the vote named the chip...
    assert est["remeshes"] == 1             # ...and the controller acted
    assert len(tr._mesh.devices.flat) == 4
    assert inj["device"] not in {d.id for d in tr._mesh.devices.flat}
    _assert_same_stream(h, l, h_ref, l_ref)
    for n in tr_ref.params:
        np.testing.assert_allclose(np.asarray(tr.params[n]),
                                   np.asarray(tr_ref.params[n]),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_transient_divergence_rolls_back_and_replays_clean(tmp_path):
    """A transient upset (breach flag with healthy data): one rollback,
    one clean replay, no quarantine — the final stream and params match
    the uninterrupted run and the mesh never shrinks."""
    tr_ref, h_ref, l_ref = _run_fit(num_epoch=2)
    tr, h, l = _run_fit(ckdir=tmp_path, num_epoch=2,
                        integrity=IntegrityConfig(period=1),
                        flag_poison_at=(0, 1))
    st = resilience.stats()["integrity"]
    assert st["divergences"] == 1
    assert st["replays"] == 1 and st["rollbacks"] == 1
    assert st["quarantines"] == 0           # transient, not poison
    assert len(tr._mesh.devices.flat) == 8  # mesh untouched
    _assert_same_stream(h, l, h_ref, l_ref)
    for n in tr_ref.params:
        np.testing.assert_allclose(np.asarray(tr.params[n]),
                                   np.asarray(tr_ref.params[n]),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_poison_batch_quarantined_after_deterministic_replay(tmp_path):
    """A NaN batch breaches, replays, breaches AGAIN at the same
    position: that is poison, not hardware — quarantine it under the
    data-guard budget and train past it."""
    tr, h, l = _run_fit(ckdir=tmp_path, num_epoch=1, nan_batch=1,
                        integrity=IntegrityConfig(period=1))
    st = resilience.stats()["integrity"]
    assert st["quarantines"] == 1
    assert st["divergences"] == 2           # original + replay
    assert st["replays"] == 2 and st["rollbacks"] == 2
    for n in tr.params:                     # trained past the poison
        assert np.isfinite(np.asarray(tr.params[n])).all(), n
    # the poison batch never reaches the callbacks: the guard raises at
    # the period boundary BEFORE them, and the final pass skips it — so
    # exactly the two clean batches are in the record
    assert sorted(h) == [(0, 0), (0, 2)]


def test_poison_quarantine_respects_skip_budget(tmp_path, monkeypatch):
    """Quarantining is bounded: past max_skipped_records the guard
    refuses to silently drop more data."""
    monkeypatch.setenv("MXNET_TPU_DATA_MAX_SKIP", "8")  # < one batch
    with pytest.raises(DataBudgetExceeded, match="budget"):
        _run_fit(ckdir=tmp_path, num_epoch=1, nan_batch=1,
                 integrity=IntegrityConfig(period=1))


def test_divergence_without_checkpoint_dir_aborts_typed():
    """No checkpoint_dir means no rollback rung: the ladder ends in a
    typed IntegrityAbort (EXIT_INTEGRITY) rather than training on."""
    from mxnet_tpu.resilience.integrity import (EXIT_INTEGRITY,
                                                IntegrityAbort)
    tr = _make_trainer(integrity=IntegrityConfig(period=1))
    guard = IntegrityGuard(tr, tr._ig_cfg, checkpoint_dir=None)
    with pytest.raises(IntegrityAbort) as exc:
        guard.recover(None, DivergenceDetected("x", epoch=0, nbatch=0))
    assert exc.value.exit_code == EXIT_INTEGRITY == 86
    from mxnet_tpu.resilience.supervisor import \
        EXIT_INTEGRITY as SUP_EXIT
    assert SUP_EXIT == EXIT_INTEGRITY


def test_fused_step_carries_sentinel_on_module_path(monkeypatch):
    """The Module/Gluon fused step rides the SAME donated-state seam:
    MXTPU_INTEGRITY_PERIOD arms the sentinel there too, loss-scale-free,
    with the classic 7-arg caller contract untouched."""
    from mxnet_tpu import perf
    from mxnet_tpu.io import DataBatch, DataDesc
    monkeypatch.setenv("MXTPU_INTEGRITY_PERIOD", "1")
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[DataDesc("data", (8, 10))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    mx.random.seed(7)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    stepper = perf.module_stepper(mod)
    assert stepper is not None
    rng = np.random.RandomState(0)
    batch = DataBatch(
        data=[mx.nd.array(rng.rand(8, 10).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))])
    for _ in range(4):
        stepper.step(batch)
    st = stepper._fused.integrity_stats()
    assert st["samples"] == 4 and st["flag"] == 0
    poison = DataBatch(
        data=[mx.nd.array(np.full((8, 10), np.nan, np.float32))],
        label=batch.label)
    stepper.step(poison)
    st = stepper._fused.integrity_stats()
    assert st["flag"] == 2 and st["samples"] == 4  # breach, not folded
    stepper._fused.reset_integrity_state()
    assert stepper._fused.integrity_stats()["flag"] == 0
    g = stepper._fused.guard
    assert g.count == 1 and not g.retraced     # one program, carry free


def test_healthy_guarded_run_keeps_monitor_silent(tmp_path, caplog):
    """checksum_rounds/votes move every period on a healthy run — the
    ResilienceMonitor must exclude them from its movement test so a
    clean guarded run logs nothing."""
    import logging as _logging

    from mxnet_tpu.callback import ResilienceMonitor
    mon = ResilienceMonitor(frequent=1)
    faults.disarm()
    resilience.reset_stats()
    X = np.random.RandomState(1).randn(48, 784).astype(np.float32)
    y = np.random.RandomState(2).randint(0, 10, (48,)).astype(np.float32)
    tr = _make_trainer(integrity=IntegrityConfig(period=1))
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    with caplog.at_level(_logging.WARNING, logger=""):
        tr.fit(it, num_epoch=1, checkpoint_dir=str(tmp_path),
               checkpoint_batch_period=1, batch_end_callback=mon)
    st = mon.stats["integrity"]
    assert st["checksum_rounds"] == 3 and st["votes"] > 0
    assert st["divergences"] == 0
    assert not [r for r in caplog.records if "Resilience" in r.message]
