"""Compiler layer (mxnet_tpu/compiler): pass framework, graph
fingerprints, persistent compilation cache.

Three contracts (docs/how_to/compiler.md):

* fingerprints are STABLE — same graph, same key, across processes —
  and SENSITIVE: any attr / shape / mesh / donation change is a new key;
* passes are value-preserving — DCE/CSE-transformed step programs are
  bitwise-identical to un-passed ones for Module, Gluon and SPMD (the
  donation-equivalence discipline of tests/test_perf_runtime.py);
* the cache can only ever cost a recompile — corrupt, truncated, or
  fault-injected (``compiler.cache.read``) entries are quarantined and
  the bind recompiles; it never serves a wrong program, never fails.

All CPU, tiny shapes, tmp-dir cache roots (the user cache is never
touched).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compiler, gluon
from mxnet_tpu.compiler import (CompilationCache, GraphIR, Pass,
                                PassContext, PassManager)
from mxnet_tpu.compiler.passes import (CommonSubexpressionElimination,
                                       DeadOpElimination)
from mxnet_tpu.gluon import nn
from mxnet_tpu.io import DataBatch, DataDesc
from mxnet_tpu.resilience import FaultPlan, faults


@pytest.fixture
def tmp_cache(tmp_path, monkeypatch):
    """Point the persistent cache at an isolated tmp root."""
    root = str(tmp_path / "executables")
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", root)
    compiler.reset_stats()
    yield root
    compiler.reset_stats()


def mlp_symbol(num_hidden=16, name_prefix=""):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=num_hidden,
                                name=name_prefix + "fc1")
    act = mx.sym.Activation(fc1, act_type="relu",
                            name=name_prefix + "relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4,
                                name=name_prefix + "fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                name=name_prefix + "softmax")


def dup_branch_symbol():
    """A graph with a REAL duplicate subexpression, so CSE actually
    rewrites it (relu(fc1) computed twice, summed)."""
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    b1 = mx.sym.Activation(fc1, act_type="relu", name="relu_a")
    b2 = mx.sym.Activation(fc1, act_type="relu", name="relu_b")
    merged = b1 + b2
    fc2 = mx.sym.FullyConnected(merged, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                name="softmax")


# ---------------------------------------------------------------------------
# fingerprints: golden stability + sensitivity
# ---------------------------------------------------------------------------

def test_fingerprint_stable_for_identical_construction():
    assert compiler.graph_fingerprint(mlp_symbol()) \
        == compiler.graph_fingerprint(mlp_symbol())


def test_fingerprint_changes_on_attr_shape_mesh_donation():
    base = compiler.graph_fingerprint(mlp_symbol())
    # attr change -> new graph fingerprint
    assert compiler.graph_fingerprint(mlp_symbol(num_hidden=32)) != base
    # name change -> new fingerprint (names are the dict calling
    # convention of the traced programs)
    assert compiler.graph_fingerprint(mlp_symbol(name_prefix="x_")) != base

    # shape change -> new PROGRAM key (structural fp is shape-free)
    import jax.numpy as jnp
    a8 = ({"data": jnp.zeros((8, 12))},)
    a4 = ({"data": jnp.zeros((4, 12))},)
    sig8, _ = compiler.fingerprint.aval_signature(a8)
    sig4, _ = compiler.fingerprint.aval_signature(a4)
    k8 = compiler.program_key("t", base, sig8)
    assert k8 != compiler.program_key("t", base, sig4)
    # donation change -> new program key
    assert k8 != compiler.program_key("t", base, sig8, donation=(0,))
    # mesh change -> new signature
    import jax
    from mxnet_tpu.parallel import make_mesh
    m1 = make_mesh({"data": 1}, devices=jax.devices()[:1])
    m2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
    assert compiler.mesh_signature(m1) != compiler.mesh_signature(m2)
    assert compiler.mesh_signature(None) == "none"


def test_fingerprint_golden_across_processes():
    """Same model code in a fresh interpreter -> the same key. This is
    the property the whole persistent cache stands on."""
    prog = (
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import compiler\n"
        "data = mx.sym.var('data')\n"
        "fc1 = mx.sym.FullyConnected(data, num_hidden=16, name='fc1')\n"
        "act = mx.sym.Activation(fc1, act_type='relu', name='relu1')\n"
        "fc2 = mx.sym.FullyConnected(act, num_hidden=4, name='fc2')\n"
        "net = mx.sym.SoftmaxOutput(fc2, mx.sym.var('softmax_label'),"
        " name='softmax')\n"
        "print(compiler.graph_fingerprint(net))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr
    child_fp = out.stdout.strip().splitlines()[-1]
    assert child_fp == compiler.graph_fingerprint(mlp_symbol(
        name_prefix=""))


def test_code_salt_override_and_stability(monkeypatch):
    s1 = compiler.code_salt()
    assert s1 == compiler.code_salt()    # process-cached
    monkeypatch.setattr(compiler.fingerprint, "_CODE_SALT", None)
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_SALT", "pinned")
    s2 = compiler.code_salt()
    assert s2 != s1
    monkeypatch.setattr(compiler.fingerprint, "_CODE_SALT", None)


# ---------------------------------------------------------------------------
# pass framework
# ---------------------------------------------------------------------------

def test_pass_manager_orders_by_requires():
    seen = []

    class A(Pass):
        name = "a"

        def run(self, ir, ctx):
            seen.append("a")
            return ir, {}

    class B(Pass):
        name = "b"
        requires = ("a",)

        def run(self, ir, ctx):
            seen.append("b")
            return ir, {}

    # registered b-first; requires puts a before b anyway
    mgr = PassManager([B(), A()])
    mgr.run(GraphIR.from_symbol(mlp_symbol()), PassContext())
    assert seen == ["a", "b"]


def test_pass_manager_rejects_unknown_and_cyclic_requires():
    class Needy(Pass):
        name = "needy"
        requires = ("nonexistent",)

        def run(self, ir, ctx):
            return ir, {}

    with pytest.raises(mx.base.MXNetError, match="unknown pass"):
        PassManager([Needy()]).schedule()

    class C1(Pass):
        name = "c1"
        requires = ("c2",)

        def run(self, ir, ctx):
            return ir, {}

    class C2(Pass):
        name = "c2"
        requires = ("c1",)

        def run(self, ir, ctx):
            return ir, {}

    with pytest.raises(mx.base.MXNetError, match="cycle"):
        PassManager([C1(), C2()]).schedule()


def test_dead_op_elimination_prunes_unreachable():
    # a Group symbol where only the first head is requested: the IR keeps
    # the full node list, DCE prunes the dead branch
    a = mx.sym.var("a")
    live = mx.sym.exp(a, name="live")
    dead = mx.sym.FullyConnected(a, num_hidden=7, name="deadfc")
    grp = mx.sym.Group([live, dead])
    ir = GraphIR.from_symbol(grp)
    ir.outputs = ir.outputs[:1]         # only 'live' requested
    before = len(ir.nodes)
    out, info = DeadOpElimination().run(ir, PassContext())
    assert info["removed"] >= 2         # deadfc + its weight/bias vars
    assert len(out.nodes) < before
    assert {n.name for n in out.nodes} == {"a", "live"}
    # the pruned graph still evaluates
    ex = out.to_symbol().simple_bind(None, grad_req="null", a=(3,))
    ex.forward(a=mx.nd.array(np.ones(3)))


def test_cse_merges_duplicates_and_respects_rng_and_aux():
    # duplicate pure subexpression: merged
    res = compiler.optimize(dup_branch_symbol())
    assert res.changed
    base_ops = GraphIR.from_symbol(dup_branch_symbol()).num_ops()
    opt_ops = GraphIR.from_symbol(res.symbol).num_ops()
    assert opt_ops < base_ops

    # sampling ops never merge (two Dropouts draw different masks)
    x = mx.sym.var("x")
    g_rng = mx.sym.Dropout(x, p=0.5) + mx.sym.Dropout(x, p=0.5)
    assert not compiler.optimize(g_rng).changed

    # aux-updating ops (BatchNorm running stats) never merge
    bn_in = mx.sym.var("bn_in")
    gamma, beta = mx.sym.var("gamma"), mx.sym.var("beta")
    mmean, mvar = mx.sym.var("mmean"), mx.sym.var("mvar")
    bn1 = mx.sym.BatchNorm(bn_in, gamma, beta, mmean, mvar, name="bn1")
    bn2 = mx.sym.BatchNorm(bn_in, gamma, beta, mmean, mvar, name="bn2")
    assert not compiler.optimize(bn1 + bn2).changed

    # stateful ops (Custom: per-invocation _op_state, user callbacks)
    # never merge — each invocation must keep firing
    @mx.operator.register("cse_probe_sqr")
    class _Prop(mx.operator.CustomOpProp):
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class _Op(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0],
                                in_data[0] * in_data[0])
            return _Op()

    cin = mx.sym.var("cin")
    c1 = mx.sym.Custom(cin, op_type="cse_probe_sqr", name="c1")
    c2 = mx.sym.Custom(cin, op_type="cse_probe_sqr", name="c2")
    assert not compiler.optimize(c1 + c2).changed


def test_cse_skips_sparse_grad_and_keeps_add_bindable():
    """Merging identical sparse_grad Embeddings would flip the weight's
    tied-weight classification and make grad_req='add' un-bindable —
    passes must never make a bind fail, so these nodes don't merge."""
    data = mx.sym.var("data")
    w = mx.sym.var("emb_weight")
    e1 = mx.sym.Embedding(data, w, input_dim=10, output_dim=4,
                          sparse_grad=True, name="e1")
    e2 = mx.sym.Embedding(data, w, input_dim=10, output_dim=4,
                          sparse_grad=True, name="e2")
    net = mx.sym.sum(e1 + e2)
    assert not compiler.optimize(net).changed
    ex = net.simple_bind(None, grad_req="add", data=(3,),
                         emb_weight=(10, 4))
    ex.forward(is_train=True, data=mx.nd.array(np.zeros(3)))


def test_cse_never_mutates_the_original_symbol():
    sym = dup_branch_symbol()
    nodes_before = [(id(n), tuple(id(p) for p, _ in n.inputs))
                    for n in sym._topo_nodes()]
    compiler.optimize(sym)
    nodes_after = [(id(n), tuple(id(p) for p, _ in n.inputs))
                   for n in sym._topo_nodes()]
    assert nodes_before == nodes_after


def test_remat_policy_budget_and_annotations(monkeypatch):
    sym = mlp_symbol()
    shapes = {"data": (8, 12), "softmax_label": (8,),
              "fc1_weight": (16, 12), "fc1_bias": (16,),
              "fc2_weight": (4, 16), "fc2_bias": (4,)}
    # no budget, no mirror: remat off
    res = compiler.optimize(sym, input_shapes=shapes)
    assert res.annotations.get("remat") is False
    # a tiny budget flips the decision and reports the byte estimate
    monkeypatch.setenv("MXTPU_REMAT_MB", "0.0001")
    res2 = compiler.optimize(sym, input_shapes=shapes)
    assert res2.annotations.get("remat") is True
    assert res2.annotations.get("remat_activation_bytes_est", 0) > 0
    assert "remat=1" in res2.transform_sig
    # the explicit mirror knob forces it regardless of budget
    monkeypatch.delenv("MXTPU_REMAT_MB")
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    assert compiler.optimize(sym, input_shapes=shapes).remat is True


def test_remat_decision_is_bitwise_neutral(monkeypatch, tmp_cache):
    """Recompute-in-backward changes the schedule, never the values."""
    def run():
        batch = DataBatch(
            data=[mx.nd.array(np.random.RandomState(3).rand(4, 12))],
            label=[mx.nd.array(
                np.random.RandomState(4).randint(0, 4, (4,)).astype(
                    np.float32))])
        mx.random.seed(9)
        mod = mx.mod.Module(mlp_symbol())
        mod.bind(data_shapes=[DataDesc("data", (4, 12))],
                 label_shapes=[DataDesc("softmax_label", (4,))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        for _ in range(2):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        arg, _ = mod.get_params()
        return {n: v.asnumpy() for n, v in arg.items()}

    plain = run()
    monkeypatch.setenv("MXTPU_REMAT_MB", "0.0001")
    remat = run()
    for n in plain:
        assert np.array_equal(plain[n], remat[n]), n


def test_annotate_slot_runs_registered_annotators():
    from mxnet_tpu.compiler import passes as passes_mod

    def annot(ir, ctx):
        return {"quant_ready": ir.num_ops()}

    passes_mod.register_annotator(annot)
    try:
        res = compiler.optimize(mlp_symbol())
        assert res.annotations.get("quant_ready", 0) > 0
    finally:
        passes_mod._ANNOTATORS.remove(annot)


def test_graph_passes_kill_switch(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "0")
    sym = dup_branch_symbol()
    res = compiler.optimize(sym)
    assert res.symbol is sym and not res.changed and not res.annotations


# ---------------------------------------------------------------------------
# pass correctness: bitwise step equivalence vs un-passed graphs
# ---------------------------------------------------------------------------

def _module_params_after_steps(sym, steps=2, disable_passes=False,
                               fused=True, seed=7):
    if disable_passes:
        os.environ["MXTPU_GRAPH_PASSES"] = "0"
    try:
        rng = np.random.RandomState(0)
        batch = DataBatch(
            data=[mx.nd.array(rng.rand(4, 12).astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 4, (4,)).astype(np.float32))])
        mx.random.seed(seed)
        mod = mx.mod.Module(sym)
        mod.bind(data_shapes=[DataDesc("data", (4, 12))],
                 label_shapes=[DataDesc("softmax_label", (4,))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.5,
                                             "momentum": 0.9})
        if fused:
            from mxnet_tpu import perf
            stepper = perf.module_stepper(mod)
            assert stepper is not None
            for _ in range(steps):
                stepper.step(batch)
            stepper.sync_to_module()
        else:
            for _ in range(steps):
                mod.forward(batch, is_train=True)
                mod.backward()
                mod.update()
        arg, _ = mod.get_params()
        return {n: v.asnumpy() for n, v in arg.items()}
    finally:
        os.environ.pop("MXTPU_GRAPH_PASSES", None)


def test_module_step_bitwise_equal_with_and_without_passes():
    sym = dup_branch_symbol()       # CSE genuinely rewrites this graph
    assert compiler.optimize(sym).changed
    for fused in (True, False):
        passed = _module_params_after_steps(sym, fused=fused)
        unpassed = _module_params_after_steps(sym, disable_passes=True,
                                              fused=fused)
        assert passed.keys() == unpassed.keys()
        for n in passed:
            assert np.array_equal(passed[n], unpassed[n]), \
                f"{n} (fused={fused})"


def test_spmd_step_bitwise_equal_with_and_without_passes():
    import jax
    from mxnet_tpu.parallel import SPMDTrainer, make_mesh

    rng = np.random.RandomState(0)
    x = rng.rand(8, 12).astype(np.float32)
    y = rng.randint(0, 4, (8,)).astype(np.float32)

    def run(disable_passes):
        if disable_passes:
            os.environ["MXTPU_GRAPH_PASSES"] = "0"
        try:
            mx.random.seed(21)
            mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
            tr = SPMDTrainer(dup_branch_symbol(), optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1,
                                               "momentum": 0.9},
                             mesh=mesh)
            tr.bind(data_shapes={"data": (8, 12)},
                    label_shapes={"softmax_label": (8,)})
            for _ in range(2):
                tr.step({"data": x, "softmax_label": y})
            arg, _ = tr.get_params()
            return {n: v.asnumpy() for n, v in arg.items()}
        finally:
            os.environ.pop("MXTPU_GRAPH_PASSES", None)

    passed, unpassed = run(False), run(True)
    for n in passed:
        assert np.array_equal(passed[n], unpassed[n]), n


def test_gluon_step_bitwise_equal_with_and_without_passes():
    def run(disable_passes):
        if disable_passes:
            os.environ["MXTPU_GRAPH_PASSES"] = "0"
        try:
            mx.random.seed(11)
            np.random.seed(11)
            net = nn.Sequential(prefix="cmp_")
            with net.name_scope():
                net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
            net.initialize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9})
            x = mx.nd.array(np.random.RandomState(3).rand(8, 12))
            y = mx.nd.array(np.random.RandomState(4).randint(0, 4, (8,)))
            loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
            for _ in range(2):
                with mx.autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                tr.step(8)
            return {k: v.data().asnumpy()
                    for k, v in net.collect_params().items()}
        finally:
            os.environ.pop("MXTPU_GRAPH_PASSES", None)

    passed, unpassed = run(False), run(True)
    assert passed.keys() == unpassed.keys() and passed
    for k in passed:
        assert np.array_equal(passed[k], unpassed[k]), k


# ---------------------------------------------------------------------------
# persistent cache: roundtrip, corruption, faults, LRU, kill switch
# ---------------------------------------------------------------------------

def test_cache_roundtrip_and_stats(tmp_cache):
    store = CompilationCache(root=tmp_cache)
    key = "ab" + "0" * 62
    assert store.get(key) is None       # miss
    store.put(key, b"payload-bytes", meta={"kind": "test"})
    assert store.get(key) == b"payload-bytes"
    st = compiler.stats()["cache"]
    assert st["hits"] == 1 and st["misses"] == 1 and st["writes"] == 1


def test_cache_corrupt_entry_quarantined_and_recompiled(tmp_cache):
    store = CompilationCache(root=tmp_cache)
    key = "cd" + "1" * 62
    store.put(key, b"x" * 256)
    bin_path, man_path = store._paths(key)
    # flip a byte: digest mismatch -> invalidation -> miss, files gone
    with open(bin_path, "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    assert store.get(key) is None
    assert compiler.stats()["cache"]["invalidations"] == 1
    assert not os.path.exists(bin_path) and not os.path.exists(man_path)

    # truncated payload: same fallback
    store.put(key, b"y" * 256)
    with open(bin_path, "r+b") as f:
        f.truncate(100)
    assert store.get(key) is None
    assert compiler.stats()["cache"]["invalidations"] == 2

    # unreadable manifest: same fallback
    store.put(key, b"z" * 64)
    with open(man_path, "w") as f:
        f.write("{not json")
    assert store.get(key) is None
    assert compiler.stats()["cache"]["invalidations"] == 3


def test_cache_read_fault_site_falls_back_to_recompile(tmp_cache):
    """An injected fault at compiler.cache.read reads as a miss — the
    executor recompiles; the run NEVER fails on cache trouble."""
    store = CompilationCache(root=tmp_cache)
    key = "ef" + "2" * 62
    store.put(key, b"good")
    faults.arm(FaultPlan().arm("compiler.cache.read", nth=1, count=1,
                               exc="ioerror"))
    try:
        assert store.get(key) is None           # fault -> miss
        assert store.get(key) == b"good"        # next read recovers
        assert faults.stats()["fired"]["compiler.cache.read"] == 1
    finally:
        faults.disarm()
        faults.reset_stats()


def test_cache_fault_during_executor_bind_still_trains(tmp_cache):
    """End-to-end: arm the fault site, bind + step a module — the
    injected cache failure costs a recompile only."""
    faults.arm(FaultPlan().arm("compiler.cache.read", nth=1, count=2,
                               exc="ioerror"))
    try:
        params = _module_params_after_steps(mlp_symbol(), fused=False)
        assert all(np.isfinite(v).all() for v in params.values())
    finally:
        faults.disarm()
        faults.reset_stats()


def test_cache_lru_eviction_bounds_size(tmp_cache):
    store = CompilationCache(root=tmp_cache, max_bytes=300)
    keys = [f"{i:02d}" + str(i) * 62 for i in range(4)]
    for i, key in enumerate(keys):
        store.put(key, bytes(120))
    assert store.total_bytes() <= 300
    assert compiler.stats()["cache"]["evictions"] >= 2
    # newest entries survive
    assert store.get(keys[-1]) is not None
    assert store.get(keys[0]) is None


def test_cache_kill_switch(tmp_cache, monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE", "0")
    import jax.numpy as jnp
    pj = compiler.PersistentJit(lambda x: x * 2, kind="t",
                                key_parts=("k",))
    out = pj(jnp.ones(3))
    assert np.allclose(np.asarray(out), 2.0)
    assert compiler.stats()["cache"]["writes"] == 0
    assert not os.path.exists(tmp_cache) or not any(os.scandir(tmp_cache))


def test_donated_programs_skip_persistence_by_default(tmp_cache,
                                                      monkeypatch):
    """Calling a deserialized DONATED executable corrupts the heap on
    this jax build for some program shapes (scan-carrying whole-step
    programs) — donated call sites must not touch the persistent store
    unless MXTPU_COMPILE_CACHE_DONATED=1 opts in explicitly."""
    import jax.numpy as jnp

    def f(xs):
        return [x + 1 for x in xs]

    pj = compiler.PersistentJit(f, kind="donated", key_parts=("d",),
                                donate_argnums=(0,))
    pj([jnp.ones(3)])
    assert compiler.stats()["cache"]["writes"] == 0
    # the opt-in enables the store for backends where it is proven safe
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DONATED", "1")
    pj2 = compiler.PersistentJit(f, kind="donated", key_parts=("d2",),
                                 donate_argnums=(0,))
    pj2([jnp.ones(3)])
    assert compiler.stats()["cache"]["writes"] == 1


def test_donated_persistence_default_gated_by_jax_version(monkeypatch):
    """The donated-program default is a jax-VERSION gate, not a blanket
    off: the 0.4.x line's deserialize_and_load drops donation aliasing
    (serialize_executable.py:57 — heap corruption on CPU, re-bisected),
    the 0.5 line rewrote that path. The env knob forces either way."""
    from mxnet_tpu.compiler import aot
    monkeypatch.delenv("MXTPU_COMPILE_CACHE_DONATED", raising=False)
    import jax
    broken = aot._donated_deserialize_broken()
    assert broken == (aot._jax_version_tuple() < (0, 5, 0))
    pj = compiler.PersistentJit(lambda xs: [x + 1 for x in xs],
                                kind="gate", key_parts=("g",),
                                donate_argnums=(0,))
    assert pj._persist_ok() == (not broken)
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DONATED", "1")
    assert pj._persist_ok() is True
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DONATED", "0")
    assert pj._persist_ok() is False
    assert aot._jax_version_tuple()[:2] == tuple(
        int(p) for p in jax.__version__.split(".")[:2])


def test_persistent_jit_warm_load_skips_tracing(tmp_cache):
    import jax.numpy as jnp
    traces = [0]

    def make(key):
        def f(x):
            traces[0] += 1
            return x * 3 + 1
        return compiler.PersistentJit(f, kind="warm-test",
                                      key_parts=(key,))

    x = jnp.arange(4, dtype=jnp.float32)
    cold = make("samekey")
    r1 = np.asarray(cold(x))
    assert traces[0] == 1
    # a FRESH wrapper (fresh jit cache) over the same identity: the
    # executable loads from disk — the python body never runs again
    warm = make("samekey")
    r2 = np.asarray(warm(x))
    assert traces[0] == 1
    assert np.array_equal(r1, r2)
    st = compiler.stats()["programs"]
    assert st["compiled"] == 1 and st["loaded"] == 1


def test_persistent_jit_corrupt_executable_recompiles(tmp_cache):
    """An entry that passes the digest but holds garbage (not a
    serialized executable) is quarantined at load and recompiled."""
    import jax.numpy as jnp

    def f(x):
        return x - 5

    pj = compiler.PersistentJit(f, kind="garbage-test", key_parts=("g",))
    x = jnp.ones(3)
    # forge the exact key the wrapper will look up, with garbage bytes
    sig, canon = compiler.fingerprint.aval_signature((x,))
    key = compiler.program_key("garbage-test", "g", canon)
    compiler.default_cache().put(key, b"not-a-pickled-executable")
    out = np.asarray(pj(x))
    assert np.allclose(out, -4.0)
    st = compiler.stats()["programs"]
    assert st["compiled"] == 1
    assert compiler.stats()["programs"].get("invalid_load", 0) == 1


def test_executor_warm_start_across_processes(tmp_cache):
    """The acceptance contract: a second process running the same model
    records cache hits and compiles nothing."""
    prog = (
        "import json\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import compiler\n"
        "from mxnet_tpu.io import DataDesc, DataBatch\n"
        "data = mx.sym.var('data')\n"
        "fc1 = mx.sym.FullyConnected(data, num_hidden=16, name='fc1')\n"
        "act = mx.sym.Activation(fc1, act_type='relu', name='relu1')\n"
        "fc2 = mx.sym.FullyConnected(act, num_hidden=4, name='fc2')\n"
        "net = mx.sym.SoftmaxOutput(fc2, mx.sym.var('softmax_label'),"
        " name='softmax')\n"
        "mod = mx.mod.Module(net)\n"
        "mod.bind(data_shapes=[DataDesc('data', (4, 12))],"
        " label_shapes=[DataDesc('softmax_label', (4,))])\n"
        "mod.init_params(mx.init.Xavier())\n"
        "batch = DataBatch(data=[mx.nd.array(np.ones((4, 12)))],"
        " label=[mx.nd.array(np.zeros(4))])\n"
        "mod.forward(batch, is_train=True)\n"
        "mod.backward()\n"
        "print(json.dumps(compiler.stats()))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXTPU_COMPILE_CACHE_DIR=tmp_cache)

    def run():
        out = subprocess.run([sys.executable, "-c", prog], env=env,
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["programs"]["compiled"] >= 2    # fwd + fwd_bwd
    assert cold["cache"]["hits"] == 0
    warm = run()
    assert warm["cache"]["hits"] >= 2
    assert warm["programs"]["loaded"] >= 2
    assert warm["programs"]["compiled"] == 0


# ---------------------------------------------------------------------------
# in-process program sharing (the executor satellite)
# ---------------------------------------------------------------------------

def test_executors_share_programs_by_fingerprint(tmp_cache):
    sym = mlp_symbol()
    shapes = dict(data=(4, 12), softmax_label=(4,))
    ex1 = sym.simple_bind(None, grad_req="write", **shapes)
    # no shared_exec threading — the fingerprint registry dedups anyway
    ex2 = sym.simple_bind(None, grad_req="write", **shapes)
    assert ex1._fwd is ex2._fwd
    assert ex1._fwd_bwd is ex2._fwd_bwd
    assert compiler.stats()["programs"]["shared"] >= 1
    # and reshape() keeps sharing through the same route
    ex3 = ex1.reshape(data=(8, 12), softmax_label=(8,))
    assert ex3._fwd is ex1._fwd


def test_placed_executor_reshape_keeps_identity_share(tmp_cache):
    """The ctx_group (placed) path is outside the fingerprint registry —
    reshape() must still reuse the per-group segment jits through the
    shared_exec identity route."""
    with mx.AttrScope(ctx_group="stage1"):
        data = mx.sym.var("data")
        fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="pl_fc1")
        act1 = mx.sym.Activation(fc1, act_type="relu")
    with mx.AttrScope(ctx_group="stage2"):
        fc2 = mx.sym.FullyConnected(act1, num_hidden=4, name="pl_fc2")
        net = mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                   name="softmax")
    g2c = {"stage1": mx.Context("cpu", 0), "stage2": mx.Context("cpu", 1)}
    ex = net.simple_bind(mx.cpu(), grad_req="write", group2ctx=g2c,
                         data=(8, 10), softmax_label=(8,))
    ex2 = ex.reshape(data=(4, 10), softmax_label=(4,))
    assert ex2._fwd is ex._fwd and ex2._fwd_bwd is ex._fwd_bwd
    out = ex2.forward(is_train=False,
                      data=mx.nd.array(np.ones((4, 10), np.float32)))
    assert out[0].shape == (4, 4)


def test_structurally_different_graphs_do_not_share(tmp_cache):
    shapes = dict(data=(4, 12), softmax_label=(4,))
    ex1 = mlp_symbol().simple_bind(None, grad_req="write", **shapes)
    ex2 = mlp_symbol(num_hidden=32).simple_bind(None, grad_req="write",
                                                **shapes)
    assert ex1._fwd is not ex2._fwd


def test_compiler_stats_shape():
    st = compiler.stats()
    assert set(st) == {"cache", "programs", "passes"}
    for k in ("hits", "misses", "invalidations", "writes", "evictions"):
        assert k in st["cache"]
    for k in ("compiled", "loaded", "bypassed", "shared"):
        assert k in st["programs"]
