"""Memory-tier lint suite: each donated-buffer lifetime checker proves
true positives AND true negatives on fixture snippets, plus inline
suppression, cross-call and cross-module donation propagation, the
`--only memory` CLI filter and `--report-hbm`, and the self-lint
contract — the committed tree's memory baseline is ZERO
(docs/how_to/tpu_lint.md, "Memory checkers")."""
import json
import os
import textwrap

from mxnet_tpu.analysis import core
from mxnet_tpu.analysis.cli import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MEMORY_RULES = {"use-after-donate", "donation-alias-leak",
                "unbounded-device-retention"}


def run_lint(tmp_path, name="snippet.py", source="", extra=None):
    """Write fixture file(s) under tmp_path and lint them all."""
    files = {name: source, **(extra or {})}
    paths = []
    for rel, src in files.items():
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(src))
        paths.append(str(full))
    return core.lint(paths, root=str(tmp_path))


def rules_of(findings):
    return {f.rule for f in findings}


def of_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# use-after-donate
# ---------------------------------------------------------------------------

def test_use_after_donate_loop_without_rebind(tmp_path):
    """The canonical bug: a donating step called in a loop with the
    same tree every iteration — iteration 2 reads the buffer
    iteration 1 donated."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0, 1))

            def train(self, params, state, batches):
                for b in batches:
                    self._step(params, state, b)   # result dropped!
                return params
    """)
    hits = of_rule(findings, "use-after-donate")
    assert hits, "loop read-after-donate must be caught"
    assert any("`params`" in h.message for h in hits)
    assert any("donating jit `self._step`" in h.message for h in hits)
    assert all("rebind" in h.message for h in hits)


def test_use_after_donate_through_donating_class(tmp_path):
    """A FusedStep-typed attribute donates its (params, states, aux)
    positions; reading the tree after the call — without rebinding —
    is the bug, even with no jax.jit in sight."""
    findings = run_lint(tmp_path, source="""
        class Harness:
            def __init__(self, step):
                self._fused = FusedStep(step)

            def run_once(self, params, states, aux, batch):
                outs = self._fused(params, states, aux, batch)
                return params, outs     # params was donated
    """)
    hits = of_rule(findings, "use-after-donate")
    assert len(hits) == 1
    assert "`params`" in hits[0].message
    assert "FusedStep" in hits[0].message


def test_use_after_donate_cross_call_propagation(tmp_path):
    """Donation propagates through a helper: `advance` passes its
    parameter to a donating jit, so calling `advance(params, b)`
    donates the caller's tree too."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Runner:
            def __init__(self, fn):
                self._fn = jax.jit(fn, donate_argnums=(0,))

            def advance(self, params, b):
                return self._fn(params, b)

        class Loop:
            def __init__(self, fn):
                self._runner = Runner(fn)

            def train(self, params, batches):
                for b in batches:
                    self._runner.advance(params, b)
                return params
    """)
    hits = of_rule(findings, "use-after-donate")
    assert hits, "cross-call donation must propagate"
    assert any("`params`" in h.message
               and h.context == "Loop.train" for h in hits)


def test_use_after_donate_cross_module_propagation(tmp_path):
    """The donating seam lives in another module; the typed-attribute
    resolution carries the donation summary across files."""
    findings = run_lint(
        tmp_path, name="pkg/loop.py", source="""
            from .runner import Runner

            class Loop:
                def __init__(self, fn):
                    self._runner = Runner(fn)

                def train(self, params, batches):
                    for b in batches:
                        self._runner.advance(params, b)
                    return params
        """,
        extra={"pkg/runner.py": """
            import jax

            class Runner:
                def __init__(self, fn):
                    self._fn = jax.jit(fn, donate_argnums=(0,))

                def advance(self, params, b):
                    return self._fn(params, b)
        """})
    hits = of_rule(findings, "use-after-donate")
    assert any(h.path == "pkg/loop.py" and h.context == "Loop.train"
               for h in hits)


def test_use_after_donate_module_level_wrapper(tmp_path):
    """`step = jax.jit(fn, donate_argnums=...)` at module level is a
    donating seam for every function in the module."""
    findings = run_lint(tmp_path, source="""
        import jax

        def _raw(params, b):
            return params

        step = jax.jit(_raw, donate_argnums=(0,))

        def drive(params, batches):
            for b in batches:
                step(params, b)
            return params
    """)
    hits = of_rule(findings, "use-after-donate")
    assert hits and any(h.context == "drive" for h in hits)


def test_rebind_pattern_is_clean(tmp_path):
    """TN: the documented pattern — rebind every tree from the call's
    results — never flags, in or out of a loop."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0, 1))

            def train(self, params, state, batches):
                for b in batches:
                    params, state = self._step(params, state, b)
                return params, state
    """)
    assert not of_rule(findings, "use-after-donate")


def test_snapshot_and_sync_back_are_clean(tmp_path):
    """TN: snapshot_tree() re-establishes ownership by convention, and
    a sync-back seam (refresh/sync_to_module/bind) clears the window."""
    findings = run_lint(tmp_path, source="""
        import jax
        from mxnet_tpu.resilience import snapshot_tree

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def checkpointed(self, params, b):
                self._step(params, b)
                snapshot_tree(params)       # host copy boundary
                return params

            def synced(self, params, b):
                self._step(params, b)
                self.refresh()              # sync-back seam
                return params
    """)
    assert not of_rule(findings, "use-after-donate")


def test_exception_fallback_read_is_clean(tmp_path):
    """TN: on the exceptional path the donating call never completed —
    the retry/fallback read (the PersistentJit.__call__ shape) is
    legitimate."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Wrapper:
            def __init__(self, fn):
                self._jit = jax.jit(fn, donate_argnums=(0,))

            def __call__(self, params, b):
                try:
                    return self._jit(params, b)
                except ValueError:
                    return self._fallback(params, b)

            def _fallback(self, params, b):
                return params
    """)
    assert not of_rule(findings, "use-after-donate")


def test_branches_do_not_poison_each_other(tmp_path):
    """TN: a donating call in the if-arm must not flag the read in the
    else-arm — only one path executes (the FusedStep.__call__ shape)."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Step:
            def __init__(self, fn):
                self._fn = jax.jit(fn, donate_argnums=(0,))

            def __call__(self, params, b, fast):
                if fast:
                    return self._fn(params, b)
                return self._fn(params, b)
    """)
    assert not of_rule(findings, "use-after-donate")


def test_use_after_donate_suppression(tmp_path):
    """`# tpu-lint: disable=use-after-donate` on the read silences that
    line and only that line."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def train(self, params, batches):
                for b in batches:
                    self._step(params, b)  # tpu-lint: disable=use-after-donate
                return params  # tpu-lint: disable=use-after-donate
    """)
    assert not of_rule(findings, "use-after-donate")


# ---------------------------------------------------------------------------
# donation-alias-leak
# ---------------------------------------------------------------------------

def test_alias_leak_self_attr_store_before_donation(tmp_path):
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0, 1))

            def cache_and_step(self, params, state, b):
                self._w0 = params["w0"]        # dies with the donation
                params, state = self._step(params, state, b)
                return params, state
    """)
    hits = of_rule(findings, "donation-alias-leak")
    assert len(hits) == 1
    assert "`params`" in hits[0].message
    assert "snapshot_tree" in hits[0].message


def test_alias_leak_append_before_donation(tmp_path):
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))
                self._log = []

            def log_and_step(self, params, b):
                self._log.append(params["loss_w"])   # leaks
                params = self._step(params, b)
                return params
    """)
    hits = of_rule(findings, "donation-alias-leak")
    assert len(hits) == 1
    assert ".append" in hits[0].message


def test_alias_after_donating_call_is_clean(tmp_path):
    """TN: aliasing the REBOUND tree (the call's result) is the fix the
    message recommends — never flagged."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def step_then_cache(self, params, b):
                params = self._step(params, b)
                self._w0 = params["w0"]     # alias of the new tree
                return params
    """)
    assert not of_rule(findings, "donation-alias-leak")


def test_snapshot_alias_is_clean(tmp_path):
    """TN: snapshot_tree() deep-copies to host — storing the snapshot
    is the documented safe idiom (resilience/async_checkpoint.py)."""
    findings = run_lint(tmp_path, source="""
        import jax
        from mxnet_tpu.resilience import snapshot_tree

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def snap_and_step(self, params, b):
                self._snap = snapshot_tree(params)
                params = self._step(params, b)
                return params
    """)
    assert not of_rule(findings, "donation-alias-leak")


def test_rebind_between_alias_and_donation_is_clean(tmp_path):
    """TN: a rebind of the tree between the alias and the donating call
    breaks the hazard — the alias points into the OLD tree."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step, init):
                self._step = jax.jit(step, donate_argnums=(0,))
                self._init = init

            def reset_and_step(self, params, b):
                self._w0 = params["w0"]
                params = self._init()       # fresh tree; alias is safe
                params = self._step(params, b)
                return params
    """)
    assert not of_rule(findings, "donation-alias-leak")


def test_alias_leak_suppression(tmp_path):
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def cache_and_step(self, params, b):
                self._w0 = params["w0"]  # tpu-lint: disable=donation-alias-leak
                params = self._step(params, b)
                return params
    """)
    assert not of_rule(findings, "donation-alias-leak")


# ---------------------------------------------------------------------------
# unbounded-device-retention
# ---------------------------------------------------------------------------

def test_retention_jit_output_appended_in_loop(tmp_path):
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step)
                self._history = []

            def train(self, params, batches):
                for b in batches:
                    loss = self._step(params, b)
                    self._history.append(loss)
                return params
    """)
    hits = of_rule(findings, "unbounded-device-retention")
    assert len(hits) == 1
    assert "`self._history`" in hits[0].message
    assert "pins its HBM buffer" in hits[0].message


def test_retention_jnp_value_in_while_loop(tmp_path):
    findings = run_lint(tmp_path, source="""
        import jax.numpy as jnp

        class Collector:
            def __init__(self):
                self._acts = []

            def collect(self, xs):
                i = 0
                while i < len(xs):
                    self._acts.append(jnp.tanh(xs[i]))
                    i += 1
    """)
    hits = of_rule(findings, "unbounded-device-retention")
    assert len(hits) == 1
    assert "`self._acts`" in hits[0].message


def test_drained_container_is_clean(tmp_path):
    """TN: a buffer with a drain anywhere in its class (the metric.py
    `_pending` idiom) is bounded-by-protocol."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Metric:
            def __init__(self, step):
                self._step = jax.jit(step)
                self._pending = []

            def update(self, params, b):
                self._pending.append(self._step(params, b))

            def get(self):
                vals = jax.device_get(self._pending)
                self._pending.clear()
                return vals
    """)
    assert not of_rule(findings, "unbounded-device-retention")


def test_host_converted_append_is_clean(tmp_path):
    """TN: converting to host at the boundary (float/device_get/
    asnumpy) releases the device buffer — nothing retained pins HBM."""
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step)
                self._history = []

            def train(self, params, batches):
                for b in batches:
                    loss = self._step(params, b)
                    self._history.append(float(loss))
                return params
    """)
    assert not of_rule(findings, "unbounded-device-retention")


def test_bounded_deque_is_clean(tmp_path):
    findings = run_lint(tmp_path, source="""
        import collections
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step)
                self._recent = collections.deque(maxlen=8)

            def train(self, params, batches):
                for b in batches:
                    self._recent.append(self._step(params, b))
                return params
    """)
    assert not of_rule(findings, "unbounded-device-retention")


def test_retention_suppression(tmp_path):
    findings = run_lint(tmp_path, source="""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step)
                self._history = []

            def train(self, params, batches):
                for b in batches:
                    loss = self._step(params, b)
                    self._history.append(loss)  # tpu-lint: disable=unbounded-device-retention
                return params
    """)
    assert not of_rule(findings, "unbounded-device-retention")


# ---------------------------------------------------------------------------
# CLI: tier filter, rule catalog, HBM report
# ---------------------------------------------------------------------------

def test_cli_only_memory_runs_just_the_tier(tmp_path, capsys):
    snippet = tmp_path / "snippet.py"
    snippet.write_text(textwrap.dedent("""
        import jax

        class Trainer:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def train(self, params, batches):
                for b in batches:
                    self._step(params, b)
                return params
    """))
    rc = lint_main([str(snippet), "--root", str(tmp_path),
                    "--only", "memory", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "use-after-donate" in out


def test_cli_list_rules_shows_memory_tier(capsys):
    rc = lint_main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rule in MEMORY_RULES:
        assert f"{rule} [memory]" in out


def test_cli_unknown_tier_mentions_memory(capsys):
    rc = lint_main(["--only", "nope", "--root", REPO])
    err = capsys.readouterr().err
    assert rc == 2
    assert "memory" in err


def test_cli_report_hbm(capsys):
    rc = lint_main(["--report-hbm"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "micro-LSTM" in out and "micro-ResNet" in out
    for contributor in ("params", "grads", "optimizer_state",
                        "activations"):
        assert contributor in out
    assert "MXTPU_HBM_BUDGET_MB" in out


# ---------------------------------------------------------------------------
# the committed tree itself
# ---------------------------------------------------------------------------

def test_repo_memory_tier_is_clean():
    """`--only memory` over the real tree exits 0: the sweep's findings
    were model-precision fixes or true-positive fixes, never baselined."""
    rc = lint_main([os.path.join(REPO, "mxnet_tpu"), "--root", REPO,
                    "--only", "memory"])
    assert rc == 0


def test_repo_memory_baseline_is_zero():
    """The memory tier lands with a ZERO grandfathered baseline — new
    findings must be fixed, not baselined (docs/how_to/tpu_lint.md)."""
    baseline = os.path.join(REPO, "tpu-lint-baseline.json")
    with open(baseline) as fh:
        entries = json.load(fh)["findings"]
    assert not [e for e in entries if e["rule"] in MEMORY_RULES]
