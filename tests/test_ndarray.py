"""NDArray semantics tests (reference model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert (a.asnumpy() == 0).all()

    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32

    c = nd.full((2, 2), 7.5)
    assert (c.asnumpy() == 7.5).all()

    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    np.testing.assert_array_equal(d.asnumpy(), [[1, 2], [3, 4]])

    e = nd.arange(1, 7, 2)
    np.testing.assert_allclose(e.asnumpy(), [1, 3, 5])


def test_elementwise_arith():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[4.0, 3.0], [2.0, 1.0]])
    np.testing.assert_allclose((a + b).asnumpy(), np.full((2, 2), 5.0))
    np.testing.assert_allclose((a - b).asnumpy(), [[-3, -1], [1, 3]])
    np.testing.assert_allclose((a * b).asnumpy(), [[4, 6], [6, 4]])
    np.testing.assert_allclose((a / b).asnumpy(), [[0.25, 2 / 3], [1.5, 4]])
    np.testing.assert_allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((1 - a).asnumpy(), [[0, -1], [-2, -3]])
    np.testing.assert_allclose((2 / a).asnumpy(), [[2, 1], [2 / 3, 0.5]])
    np.testing.assert_allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]], rtol=1e-5)
    np.testing.assert_allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])


def test_inplace_mutation():
    a = nd.zeros((2, 3))
    a[:] = 5
    assert (a.asnumpy() == 5).all()
    a += 1
    assert (a.asnumpy() == 6).all()
    a *= 2
    assert (a.asnumpy() == 12).all()
    a[0, 1] = 99
    assert a.asnumpy()[0, 1] == 99
    a[1] = nd.array([7.0, 8.0, 9.0])
    np.testing.assert_allclose(a.asnumpy()[1], [7, 8, 9])


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    np.testing.assert_array_equal(a[1].asnumpy(), np.arange(12, 24).reshape(3, 4))
    np.testing.assert_array_equal(a[1, 2].asnumpy(), [20, 21, 22, 23])
    np.testing.assert_array_equal(a[:, 1].asnumpy(), [[4, 5, 6, 7], [16, 17, 18, 19]])
    sl = a[0:1]
    assert sl.shape == (1, 3, 4)


def test_copy_semantics():
    a = nd.ones((3,))
    b = a.copy()
    b[:] = 2
    assert (a.asnumpy() == 1).all()

    c = nd.zeros((3,))
    a.copyto(c)
    assert (c.asnumpy() == 1).all()

    d = a.as_in_context(mx.cpu(0))
    assert d.context.device_type == "cpu"


def test_scalar_conversion():
    a = nd.array([3.5])
    assert a.asscalar() == 3.5
    assert float(a) == 3.5
    with pytest.raises(Exception):
        nd.zeros((2,)).asscalar()


def test_reshape_transpose():
    a = nd.array(np.arange(6).reshape(2, 3))
    assert a.reshape((3, 2)).shape == (3, 2)
    assert a.reshape((-1,)).shape == (6,)
    assert a.T.shape == (3, 2)
    assert a.transpose().shape == (3, 2)
    assert a.expand_dims(0).shape == (1, 2, 3)
    assert nd.moveaxis(a, 0, 1).shape == (3, 2)


def test_reduce_methods():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.sum().asscalar() == 15
    np.testing.assert_allclose(a.sum(axis=0).asnumpy(), [3, 5, 7])
    np.testing.assert_allclose(a.mean(axis=1).asnumpy(), [1, 4])
    assert a.max().asscalar() == 5
    assert a.min().asscalar() == 0
    assert a.argmax().asscalar() == 5
    assert a.norm().asscalar() == pytest.approx(np.sqrt(np.sum(np.arange(6) ** 2)))


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a == b).asnumpy(), [0, 1, 0])
    np.testing.assert_array_equal((a > b).asnumpy(), [0, 0, 1])
    np.testing.assert_array_equal((a >= 2).asnumpy(), [0, 1, 1])


def test_dtype_cast():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = nd.cast(a, dtype="float16")
    assert c.dtype == np.float16


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs.npz")
    arrs = {"w": nd.ones((2, 2)), "b": nd.zeros((3,))}
    nd.save(fname, arrs)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    np.testing.assert_array_equal(loaded["w"].asnumpy(), np.ones((2, 2)))

    lst = [nd.ones((2,)), nd.zeros((1,))]
    fname2 = str(tmp_path / "lst.npz")
    nd.save(fname2, lst)
    loaded2 = nd.load(fname2)
    assert isinstance(loaded2, list) and len(loaded2) == 2


def test_context():
    assert mx.cpu(0).device_type == "cpu"
    with mx.Context("cpu", 0):
        assert mx.current_context().device_type == "cpu"
    a = nd.zeros((2,), ctx=mx.cpu(0))
    assert a.context.device_type == "cpu"
    a.wait_to_read()


def test_concat_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concatenate([a, b], axis=0)
    assert c.shape == (4, 3)
    parts = c.split(2, axis=0)
    assert len(parts) == 2
    np.testing.assert_array_equal(parts[0].asnumpy(), np.ones((2, 3)))


def test_broadcast():
    a = nd.array([[1.0], [2.0]])
    b = a.broadcast_to((2, 3))
    assert b.shape == (2, 3)
    np.testing.assert_allclose(b.asnumpy(), [[1, 1, 1], [2, 2, 2]])


def test_getitem_bounds_checked_under_record():
    import mxnet_tpu as mx
    x = mx.nd.array(np.array([1., 2., 3.], np.float32))
    with mx.autograd.record():
        with pytest.raises(IndexError):
            x[5]
        with pytest.raises(IndexError):
            x[-5]


def test_scalar_tuple_index_grad():
    import mxnet_tpu as mx
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.attach_grad()
    with mx.autograd.record():
        y = x[0, 1] * 3
    y.backward()
    g = x.grad.asnumpy()
    exp = np.zeros((2, 3), np.float32)
    exp[0, 1] = 3
    np.testing.assert_allclose(g, exp)


def test_T_property_grad():
    import mxnet_tpu as mx
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.attach_grad()
    with mx.autograd.record():
        loss = (x.T * 2).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 3), 2.0))


def test_zero_size_indexing():
    import mxnet_tpu as mx
    x = mx.nd.zeros((5, 0))
    with mx.autograd.record():
        y = x[2]
    assert y.shape == (0,)


def test_bool_and_empty_slice_indexing_under_record():
    import mxnet_tpu as mx
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.attach_grad()
    with mx.autograd.record():
        b = x[True]
        e = x[0, 1:1]
        loss = (b * 2).sum()
    assert b.shape == (1, 2, 3)  # numpy semantics: new leading axis
    assert e.shape == (0,)
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 3), 2.0))
