"""Initializer + RNG suites (reference: tests/python/unittest/test_init.py
and test_random.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _init_array(init, name="weight", shape=(50, 100)):
    arr = nd.zeros(shape)
    desc = mx.init.InitDesc(name)
    init(desc, arr)
    return arr.asnumpy()


def test_constant_zero_one():
    assert (_init_array(mx.init.Zero()) == 0).all()
    assert (_init_array(mx.init.One()) == 1).all()
    assert (_init_array(mx.init.Constant(2.5)) == 2.5).all()


def test_uniform_normal_ranges():
    u = _init_array(mx.init.Uniform(0.3))
    assert np.abs(u).max() <= 0.3 and np.abs(u).std() > 0
    n = _init_array(mx.init.Normal(2.0), shape=(200, 200))
    assert abs(n.std() - 2.0) < 0.1


def test_xavier_scales_with_fan():
    x = _init_array(mx.init.Xavier(factor_type="avg", magnitude=3),
                    shape=(100, 400))
    bound = np.sqrt(3.0 / ((100 + 400) / 2))
    assert np.abs(x).max() <= bound + 1e-6
    # gaussian variant
    g = _init_array(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                   magnitude=2), shape=(300, 300))
    assert abs(g.std() - np.sqrt(2.0 / 300)) < 0.01


def test_name_based_defaults():
    """Initializer dispatches on name suffix (reference __call__)."""
    init = mx.init.Uniform(0.1)
    bias = nd.zeros((10,))
    init(mx.init.InitDesc("fc1_bias"), bias)
    assert (bias.asnumpy() == 0).all()
    gamma = nd.zeros((10,))
    init(mx.init.InitDesc("bn_gamma"), gamma)
    assert (gamma.asnumpy() == 1).all()
    mean = nd.zeros((10,))
    init(mx.init.InitDesc("bn_moving_mean"), mean)
    assert (mean.asnumpy() == 0).all()
    var = nd.zeros((10,))
    init(mx.init.InitDesc("bn_moving_var"), var)
    assert (var.asnumpy() == 1).all()


def test_orthogonal_and_bilinear():
    o = _init_array(mx.init.Orthogonal(), shape=(32, 64))
    gram = o @ o.T
    np.testing.assert_allclose(gram, np.eye(32) * gram[0, 0], atol=1e-3)
    b = _init_array(mx.init.Bilinear(), shape=(1, 1, 4, 4))
    assert b.max() <= 1.0 and b.min() >= 0.0


def test_mixed_initializer():
    mixed = mx.init.Mixed([".*bias", ".*"],
                          [mx.init.Zero(), mx.init.One()])
    b = nd.array(np.full((4,), 9, np.float32))
    w = nd.array(np.full((4,), 9, np.float32))
    mixed(mx.init.InitDesc("fc_bias"), b)
    mixed(mx.init.InitDesc("fc_weight"), w)
    assert (b.asnumpy() == 0).all() and (w.asnumpy() == 1).all()


# ------------------------------ random -----------------------------------


def test_seed_reproducibility():
    mx.random.seed(42)
    a = nd.random_normal(shape=(100,)).asnumpy()
    mx.random.seed(42)
    b = nd.random_normal(shape=(100,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = nd.random_normal(shape=(100,)).asnumpy()
    assert np.abs(b - c).max() > 0


@pytest.mark.parametrize("op,kw,mean,std", [
    ("random_uniform", {"low": -1.0, "high": 1.0}, 0.0, 2 / np.sqrt(12)),
    ("random_normal", {"loc": 2.0, "scale": 3.0}, 2.0, 3.0),
    ("random_exponential", {"lam": 4.0}, 0.25, 0.25),
    ("random_poisson", {"lam": 4.0}, 4.0, 2.0),
    ("random_gamma", {"alpha": 9.0, "beta": 0.5}, 4.5, 1.5),
])
def test_sampler_moments(op, kw, mean, std):
    mx.random.seed(0)
    fn = getattr(nd, op)
    x = fn(shape=(40000,), **kw).asnumpy()
    assert abs(x.mean() - mean) < 5 * std / np.sqrt(len(x)) * 3 + 0.02
    assert abs(x.std() - std) / std < 0.1


def test_multinomial_distribution():
    mx.random.seed(1)
    probs = nd.array(np.array([[0.2, 0.8]], np.float32))
    draws = nd.sample_multinomial(probs, shape=10000).asnumpy()
    assert abs(draws.mean() - 0.8) < 0.02
