"""Preemption-aware training supervisor (resilience/supervisor.py,
docs/how_to/preemption.md).

Signal, stall and crash-loop paths with injectable clocks and signal
delivery ONLY — zero real sleeps, zero real process signals (the chaos
smoke ``ci/preempt_smoke.py`` covers the real-SIGTERM leg).
"""
import hashlib
import json
import os
import signal as _signal

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import resilience
from mxnet_tpu.resilience import (CrashLoopGuard, FaultPlan, ImmediateAbort,
                                  Preempted, StallAbort, StallWatchdog,
                                  StepStalled, TrainingSupervisor, faults)
from mxnet_tpu.resilience.data import DataBudgetExceeded, DataGuardPolicy
from mxnet_tpu.resilience.supervisor import (EXIT_ABORTED, EXIT_PREEMPTED,
                                             EXIT_STALLED, SITE_HEARTBEAT,
                                             SITE_SIGNAL, read_preempt_marker,
                                             signal_runtime)


@pytest.fixture(autouse=True)
def _clean():
    faults.disarm()
    resilience.reset_stats()
    yield
    faults.disarm()
    resilience.reset_stats()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _sup(**kw):
    kw.setdefault("signals", ())
    kw.setdefault("sleep", lambda s: None)
    return TrainingSupervisor(**kw)


# -- registry ----------------------------------------------------------------

def test_sites_registered():
    assert SITE_SIGNAL == "supervisor.signal"
    assert SITE_HEARTBEAT == "supervisor.heartbeat"
    assert SITE_SIGNAL in resilience.SITES
    assert SITE_HEARTBEAT in resilience.SITES


def test_stats_surface():
    s = resilience.stats()["supervisor"]
    for key in ("signals", "second_signals", "preempt_exits", "aborts",
                "stalls", "stall_retries", "stall_rebinds",
                "stall_remeshes", "stall_aborts", "crash_resumes",
                "batches_quarantined", "crash_backoff_s"):
        assert key in s


# -- signal semantics --------------------------------------------------------

def test_first_signal_sets_flag_only():
    sup = _sup()
    with sup.attach():
        assert not sup.preempt_requested
        signal_runtime().deliver(int(_signal.SIGTERM))
        assert sup.preempt_requested
        assert sup.check_preempt()
    assert resilience.stats()["supervisor"]["signals"] == 1


def test_second_signal_immediate_abort():
    sup = _sup()
    with sup.attach():
        signal_runtime().deliver(int(_signal.SIGTERM))
        with pytest.raises(ImmediateAbort) as err:
            signal_runtime().deliver(int(_signal.SIGTERM))
        assert err.value.exit_code == EXIT_ABORTED
    # ImmediateAbort is a BaseException: it must escape `except Exception`
    assert not isinstance(ImmediateAbort("x"), Exception)
    assert resilience.stats()["supervisor"]["second_signals"] == 1


def test_injected_signal_fault_simulates_sigterm():
    faults.arm(FaultPlan().arm(SITE_SIGNAL, nth=2))
    sup = _sup()
    with sup.attach():
        assert not sup.check_preempt()      # call 1: no fault
        assert sup.check_preempt()          # call 2: injected SIGTERM
        assert sup.preempt_requested


def test_signal_filter_and_abort_still_reaches_all_listeners():
    # a listener subscribed to SIGTERM only must not see SIGINT; and an
    # ImmediateAbort from one listener (the trainer's second-signal
    # path) must not starve the others (the server's close path)
    seen = []

    class Listener:
        def __init__(self, name, abort=False):
            self.name, self.abort = name, abort

        def on_signal(self, signum):
            seen.append((self.name, signum))
            if self.abort:
                raise ImmediateAbort("now")

    rt = signal_runtime()
    aborter = Listener("aborter", abort=True)
    server = Listener("server")
    term_only = Listener("term-only")
    rt.subscribe(aborter, ())
    rt.subscribe(server, ())
    rt.subscribe(term_only, (int(_signal.SIGTERM),))
    try:
        with pytest.raises(ImmediateAbort):
            rt.deliver(int(_signal.SIGINT))
        # everyone subscribed to SIGINT saw it, despite the abort;
        # the SIGTERM-only listener did not
        assert ("aborter", int(_signal.SIGINT)) in seen
        assert ("server", int(_signal.SIGINT)) in seen
        assert all(n != "term-only" for n, _ in seen)
    finally:
        rt.unsubscribe(aborter)
        rt.unsubscribe(server)
        rt.unsubscribe(term_only)


def test_unsubscribed_after_detach():
    sup = _sup()
    with sup.attach():
        pass
    signal_runtime().deliver(int(_signal.SIGTERM))
    assert not sup.preempt_requested        # no longer listening


# -- watchdog true/false positives -------------------------------------------

def test_watchdog_trips_on_stale_heartbeat():
    clock = FakeClock()
    wd = StallWatchdog(timeout=10.0, clock=clock)
    wd.beat()
    clock.advance(10.5)
    assert wd.check() is True
    assert wd.stale_for() == pytest.approx(10.5)


def test_watchdog_false_positive_slow_but_progressing():
    # a slow step that still heartbeats within the timeout never trips
    clock = FakeClock()
    wd = StallWatchdog(timeout=10.0, clock=clock)
    for _ in range(20):
        wd.beat()
        clock.advance(9.0)      # slow, but inside the budget
        assert wd.check() is False


def test_watchdog_escalation_async_raise_then_hard_abort():
    clock = FakeClock()
    aborted = []
    raised = []
    wd = StallWatchdog(timeout=5.0, clock=clock, grace=7.0,
                       hard_abort=aborted.append)
    wd._async_raise = lambda: raised.append(True)   # no real async exc
    wd._target_tid = 1                              # thread mode armed
    wd.beat()
    clock.advance(6.0)
    assert wd.check() is True
    assert raised and not aborted       # first: raise into the thread
    clock.advance(6.0)
    assert wd.check() is True
    assert not aborted                  # still inside the grace window
    clock.advance(2.0)
    wd.check()
    assert aborted == [EXIT_STALLED]    # wedged in C: hard abort
    wd.beat()
    clock.advance(1.0)
    assert wd.check() is False          # a beat stands the watchdog down


# -- the escalation ladder (run_step) ----------------------------------------

def test_ladder_rung1_retry_clears_transient_stall():
    faults.arm(FaultPlan().arm(SITE_HEARTBEAT, nth=1))
    sup = _sup()
    calls = []
    out = sup.run_step(lambda: calls.append(1) or "ok")
    assert out == "ok" and len(calls) == 1
    s = resilience.stats()["supervisor"]
    assert s["stalls"] == 1 and s["stall_retries"] == 1
    assert s["stall_rebinds"] == 0


def test_ladder_rung2_rebind():
    faults.arm(FaultPlan().arm(SITE_HEARTBEAT, nth=1, count=2))
    sup = _sup()
    rebinds = []
    out = sup.run_step(lambda: "ok", rebind=lambda: rebinds.append(1))
    assert out == "ok" and rebinds == [1]
    s = resilience.stats()["supervisor"]
    assert s["stall_retries"] == 1 and s["stall_rebinds"] == 1


def test_ladder_rung3_remesh_escalates_to_caller():
    # 4 consecutive stalls: retry, rebind, re-mesh escalation, then the
    # post-recovery re-entry stalls once more -> abort rung
    faults.arm(FaultPlan().arm(SITE_HEARTBEAT, nth=1, count=4))
    sup = _sup()
    sup.can_remesh = True

    class Escalate(Exception):
        pass

    with pytest.raises(Escalate):
        sup.run_step(lambda: "ok", rebind=lambda: None,
                     remesh_exc=lambda err: Escalate(str(err)))
    s = resilience.stats()["supervisor"]
    assert s["stall_remeshes"] == 1 and s["stall_aborts"] == 0
    # the streak survives the re-mesh: a still-stalling step goes
    # straight to the abort rung instead of ping-ponging
    aborted = []
    with pytest.raises(StallAbort) as err:
        sup.run_step(lambda: "ok", rebind=lambda: None,
                     remesh_exc=lambda e: Escalate(str(e)),
                     on_abort=lambda e: aborted.append(e))
    assert err.value.exit_code == EXIT_STALLED
    assert aborted


def test_ladder_abort_without_remesh():
    # no remesh hook (Module path): retry -> rebind -> abort
    faults.arm(FaultPlan().arm(SITE_HEARTBEAT, nth=1, count=5))
    sup = _sup()
    aborted = []
    with pytest.raises(StallAbort):
        sup.run_step(lambda: "ok", rebind=lambda: None,
                     on_abort=lambda e: aborted.append(e))
    assert len(aborted) == 1
    assert resilience.stats()["supervisor"]["stall_aborts"] == 1


def test_ladder_streak_resets_on_success():
    faults.arm(FaultPlan().arm(SITE_HEARTBEAT, nth=1)
               .arm(SITE_HEARTBEAT, nth=3))
    sup = _sup()
    sup.run_step(lambda: "a")       # stall -> retry -> ok (streak reset)
    sup.run_step(lambda: "b")       # stall -> retry -> ok again
    s = resilience.stats()["supervisor"]
    assert s["stall_retries"] == 2 and s["stall_rebinds"] == 0


def test_ladder_catches_mid_step_stall():
    # a watchdog async-raise lands INSIDE the step body, not at the
    # heartbeat: the ladder must catch that too
    sup = _sup()
    state = {"n": 0}

    def step():
        state["n"] += 1
        if state["n"] == 1:
            raise StepStalled("async raise mid-step")
        return "ok"

    assert sup.run_step(step) == "ok"
    assert resilience.stats()["supervisor"]["stall_retries"] == 1


# -- crash-loop guard --------------------------------------------------------

def test_crash_loop_backoff_schedule(tmp_path):
    slept = []
    path = str(tmp_path / "r.json")
    guard = CrashLoopGuard(path, limit=5, backoff_base=2.0,
                           backoff_cap=10.0, sleep=slept.append)
    assert guard.on_resume(0, 3) == "fresh"
    assert slept == []
    assert guard.on_resume(0, 3) == "retry"        # attempt 2: base
    assert guard.on_resume(0, 3) == "retry"        # attempt 3: 2*base
    assert guard.on_resume(0, 3) == "retry"        # attempt 4: 4*base
    assert guard.on_resume(0, 3) == "retry"        # attempt 5: capped
    assert slept == [2.0, 4.0, 8.0, 10.0]
    assert resilience.stats()["supervisor"]["crash_backoff_s"] == 24.0
    # persisted beside the manifest, atomic
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["attempts"] == 5 and doc["position"] == [0, 3]


def test_crash_loop_position_change_resets(tmp_path):
    slept = []
    guard = CrashLoopGuard(str(tmp_path / "r.json"), limit=3,
                           backoff_base=1.0, sleep=slept.append)
    guard.on_resume(0, 3)
    guard.on_resume(0, 3)
    assert guard.on_resume(1, 0) == "fresh"        # progress between crashes
    assert guard.attempts == 1


def test_crash_loop_quarantines_poison_batch(tmp_path):
    path = str(tmp_path / "r.json")
    guard = CrashLoopGuard(path, limit=2, backoff_base=0.0,
                           sleep=lambda s: None)
    assert guard.on_resume(0, 3) == "fresh"
    assert guard.on_resume(0, 3) == "retry"
    assert guard.on_resume(0, 3) == "quarantine"
    assert guard.is_quarantined(0, 3)
    assert guard.attempts == 0                     # counter starts over
    assert resilience.stats()["supervisor"]["batches_quarantined"] == 1
    # a NEW guard over the same file sees the quarantine (persisted)
    guard2 = CrashLoopGuard(path, limit=2, sleep=lambda s: None)
    assert guard2.is_quarantined(0, 3)


def test_crash_loop_quarantine_respects_data_budget(tmp_path):
    policy = DataGuardPolicy(max_skipped_records=1, poison_threshold=8,
                             max_quarantined_shards=1)
    guard = CrashLoopGuard(str(tmp_path / "r.json"), limit=1,
                           backoff_base=0.0, policy=policy,
                           sleep=lambda s: None)
    guard.on_resume(0, 1)
    assert guard.on_resume(0, 1) == "quarantine"   # budget: 1/1 used
    guard.on_resume(0, 2)
    with pytest.raises(DataBudgetExceeded):
        guard.on_resume(0, 2)                      # would exceed budget


def test_crash_loop_note_progress_resets(tmp_path):
    guard = CrashLoopGuard(str(tmp_path / "r.json"), limit=3,
                           backoff_base=0.0, sleep=lambda s: None)
    guard.on_resume(0, 3)
    guard.on_resume(0, 3)
    guard.note_progress()
    assert guard.attempts == 0
    assert guard.on_resume(0, 3) == "fresh"


def test_crash_loop_unreadable_file_resets_not_raises(tmp_path):
    path = str(tmp_path / "r.json")
    with open(path, "w", encoding="utf-8") as f:
        f.write("{torn")
    guard = CrashLoopGuard(path, limit=3, sleep=lambda s: None)
    assert guard.attempts == 0
    assert guard.on_resume(0, 0) == "fresh"


# -- Module.fit integration ---------------------------------------------------

def _mlp():
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


_rng = np.random.RandomState(0)
_X = _rng.rand(96, 8).astype(np.float32)
_Y = _rng.randint(0, 4, (96,)).astype(np.float32)


def _fit(nep, prefix=None, sup=None, resume=None, preempt_at=None,
         recs=None, batch_period=None):
    mx.random.seed(7)
    mod = mx.mod.Module(_mlp(), data_names=["data"],
                        label_names=["softmax_label"])
    it = mx.io.NDArrayIter(_X, _Y, batch_size=16, shuffle=True, seed=3,
                           label_name="softmax_label")

    def cb(param):
        b = param.locals["batch"]
        h = hashlib.sha256(np.ascontiguousarray(
            b.data[0].asnumpy()).tobytes()).hexdigest()[:12]
        if recs is not None:
            recs.append((param.epoch, param.nbatch, h))
        if preempt_at is not None \
                and (param.epoch, param.nbatch) == preempt_at:
            signal_runtime().deliver(int(_signal.SIGTERM))

    mod.fit(it, num_epoch=nep, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier(), batch_end_callback=cb,
            checkpoint_prefix=prefix, checkpoint_batch_period=batch_period,
            resume=resume, supervisor=sup)
    return mod


def test_fit_preempt_checkpoint_marker_and_bitwise_resume(tmp_path):
    ref = []
    _fit(2, recs=ref)
    assert len(ref) == 12

    prefix = str(tmp_path / "ck")
    killed = []
    with pytest.raises(Preempted) as err:
        _fit(2, prefix=prefix, sup=_sup(), preempt_at=(0, 3), recs=killed)
    assert err.value.exit_code == EXIT_PREEMPTED
    assert len(killed) == 4                 # the in-flight step finished
    marker = read_preempt_marker(prefix)
    assert marker and marker["clean"] and marker["exit_code"] == 83
    assert (marker["epoch"], marker["nbatch"]) == (0, 3)
    assert resilience.stats()["supervisor"]["preempt_exits"] == 1

    resumed = []
    _fit(2, prefix=prefix, sup=_sup(), resume="auto", recs=resumed)
    assert killed + resumed == ref          # bitwise-exact continuation
    assert read_preempt_marker(prefix) is None   # marker consumed


def test_fit_preempt_on_checkpoint_batch_keeps_the_stem(tmp_path):
    # a preemption landing on the very batch a checkpoint_batch_period
    # save just captured computes the SAME mid-epoch label — the saver
    # must reuse that stem, not delete-then-rewrite (and then roll) it
    ref = []
    _fit(2, recs=ref)
    prefix = str(tmp_path / "ck")
    killed = []
    with pytest.raises(Preempted):
        _fit(2, prefix=prefix, sup=_sup(), preempt_at=(0, 1), recs=killed,
             batch_period=2)             # bperiod save fires at nbatch=1
    from mxnet_tpu.resilience.checkpoint import (find_checkpoints,
                                                 mid_epoch_label)
    assert mid_epoch_label(0, 1) in find_checkpoints(prefix)
    resumed = []
    _fit(2, prefix=prefix, sup=_sup(), resume="auto", recs=resumed)
    assert killed + resumed == ref


def test_watchdog_suspend_covers_unsupervised_windows():
    # between run_step calls (eval, checkpoint writes) the watchdog is
    # suspended: arbitrary beat-less time must not read as a stall
    clock = FakeClock()
    wd = StallWatchdog(timeout=5.0, clock=clock)
    sup = _sup(watchdog=wd, stall_timeout=5.0)
    sup.run_step(lambda: "ok")
    clock.advance(1000.0)               # a long eval pass, no heartbeats
    assert wd.check() is False
    sup.run_step(lambda: "ok")          # heartbeat re-arms, still fine
    assert wd.check() is False


def test_fit_double_signal_aborts_without_checkpoint(tmp_path):
    prefix = str(tmp_path / "ck")
    delivered = []

    def double(param):
        if (param.epoch, param.nbatch) == (0, 1) and not delivered:
            delivered.append(1)
            signal_runtime().deliver(int(_signal.SIGTERM))
            with pytest.raises(ImmediateAbort) as err:
                signal_runtime().deliver(int(_signal.SIGTERM))
            assert err.value.exit_code == EXIT_ABORTED
            raise err.value                 # as the real handler would

    mx.random.seed(7)
    mod = mx.mod.Module(_mlp(), data_names=["data"],
                        label_names=["softmax_label"])
    it = mx.io.NDArrayIter(_X, _Y, batch_size=16,
                           label_name="softmax_label")
    with pytest.raises(ImmediateAbort):
        mod.fit(it, num_epoch=1, optimizer="sgd",
                initializer=mx.init.Xavier(), batch_end_callback=double,
                checkpoint_prefix=prefix, supervisor=_sup())
    # the abort wrote NOTHING new — no checkpoint, no clean marker
    from mxnet_tpu.resilience.checkpoint import find_checkpoints
    assert find_checkpoints(prefix) == []
    assert read_preempt_marker(prefix) is None


def test_fit_stall_ladder_retry_and_rebind(tmp_path):
    faults.arm(FaultPlan().arm(SITE_HEARTBEAT, nth=3, count=2))
    _fit(2, sup=_sup())
    s = resilience.stats()["supervisor"]
    assert s["stall_retries"] == 1 and s["stall_rebinds"] == 1
    assert s["stall_aborts"] == 0


def test_fit_stall_abort_checkpoints_last_trained_position(tmp_path):
    prefix = str(tmp_path / "ck")
    faults.arm(FaultPlan().arm(SITE_HEARTBEAT, nth=3, count=10))
    recs = []
    with pytest.raises(StallAbort) as err:
        _fit(2, prefix=prefix, sup=_sup(), recs=recs)
    assert err.value.exit_code == EXIT_STALLED
    from mxnet_tpu.resilience.checkpoint import find_checkpoints
    cks = find_checkpoints(prefix)
    assert cks, "abort must leave a checkpoint for the relaunch"
    # resume replays the stalled batch: the combined stream stays exact
    ref = []
    _fit(2, recs=ref)
    faults.disarm()
    resumed = []
    _fit(2, prefix=prefix, sup=_sup(), resume="auto", recs=resumed)
    assert recs + resumed == ref


def test_fit_resume_skips_quarantined_batch(tmp_path):
    ref = []
    _fit(2, recs=ref)
    prefix = str(tmp_path / "ck")
    killed = []
    with pytest.raises(Preempted):
        _fit(2, prefix=prefix, sup=_sup(), preempt_at=(0, 2), recs=killed)
    # simulate a crash loop at the resume position (0, 3): pre-seed the
    # attempt counter at the limit, so the next resume quarantines it
    sup = _sup(crash_limit=2, backoff_base=0.0)
    guard = sup.crash_guard(prefix)
    assert guard.on_resume(0, 3) == "fresh"
    assert guard.on_resume(0, 3) == "retry"
    resumed = []
    _fit(2, prefix=prefix, sup=sup, resume="auto", recs=resumed)
    # batch (0,3) was quarantined and skipped: the resumed stream starts
    # at the NEXT batch of the reference ordering
    assert resilience.stats()["supervisor"]["batches_quarantined"] == 1
    assert resumed[0][:2] == (0, 4)
    assert resumed[0][2] == ref[4][2]       # same shuffled stream, batch 4
    assert len(killed) + 1 + len(resumed) == len(ref)


def test_fresh_fit_clears_stale_marker(tmp_path):
    prefix = str(tmp_path / "ck")
    with pytest.raises(Preempted):
        _fit(2, prefix=prefix, sup=_sup(), preempt_at=(0, 1))
    assert read_preempt_marker(prefix) is not None
    _fit(1, prefix=prefix, sup=_sup())      # fresh lineage, no resume
    assert read_preempt_marker(prefix) is None


# -- stale-stem GC (discovery/startup sweep) ---------------------------------

def _write_ck(prefix, label):
    from mxnet_tpu.resilience.checkpoint import write_checkpoint
    sym = _mlp()
    arg = {"fc1_weight": mx.nd.zeros((16, 8)),
           "fc1_bias": mx.nd.zeros((16,)),
           "fc2_weight": mx.nd.zeros((4, 16)),
           "fc2_bias": mx.nd.zeros((4,))}
    write_checkpoint(prefix, label, sym, arg, {})


def test_find_checkpoints_supersession_order(tmp_path):
    from mxnet_tpu.resilience.checkpoint import (find_checkpoints,
                                                 mid_epoch_label)
    prefix = str(tmp_path / "ck")
    # stale mid stems of epoch 0 + the end-of-epoch-1 checkpoint that
    # supersedes them (abnormal exit killed the sweep)
    _write_ck(prefix, mid_epoch_label(0, 1))
    _write_ck(prefix, mid_epoch_label(0, 3))
    _write_ck(prefix, 1)
    # raw-label ordering would put the (huge) mid labels first and make
    # resume='auto' pick a STALE stem; supersession order must not
    assert find_checkpoints(prefix)[0] == 1


def test_sweep_stale_checkpoints(tmp_path):
    from mxnet_tpu.resilience.checkpoint import (find_checkpoints,
                                                 mid_epoch_label,
                                                 sweep_stale_checkpoints)
    prefix = str(tmp_path / "ck")
    _write_ck(prefix, mid_epoch_label(0, 1))
    _write_ck(prefix, mid_epoch_label(0, 3))
    _write_ck(prefix, 1)
    _write_ck(prefix, mid_epoch_label(1, 0))    # newer than epoch-1 end
    assert sweep_stale_checkpoints(prefix) == 2
    assert sorted(find_checkpoints(prefix)) == [1, mid_epoch_label(1, 0)]
    # bounded by the USED checkpoint: a fallback resume must not delete
    # stems newer than what it actually loaded
    assert sweep_stale_checkpoints(prefix, used=1) == 0
    assert sorted(find_checkpoints(prefix)) == [1, mid_epoch_label(1, 0)]


def test_resume_sweeps_stale_stems(tmp_path):
    from mxnet_tpu.resilience.checkpoint import (find_checkpoints,
                                                 mid_epoch_label)
    prefix = str(tmp_path / "ck")
    killed = []
    with pytest.raises(Preempted):
        _fit(2, prefix=prefix, sup=_sup(), preempt_at=(1, 2), recs=killed,
             batch_period=2)
    # strand a stale superseded stem, as a kill between save and roll
    # would (older than everything on disk)
    _write_ck(prefix, mid_epoch_label(0, 0))
    assert mid_epoch_label(0, 0) in find_checkpoints(prefix)
    _fit(2, prefix=prefix, sup=_sup(), resume="auto")
    assert mid_epoch_label(0, 0) not in find_checkpoints(prefix)


# -- serving graceful drain ---------------------------------------------------

def _server(**kw):
    from mxnet_tpu.serving import CallableBackend, InferenceServer
    backend = CallableBackend(
        lambda inputs: [np.asarray(inputs["data"]).sum(axis=1)])
    srv = InferenceServer(backend, workers=0, **kw)
    srv.warm_up()
    srv.install_signal_handlers(signals=())
    return srv


def test_serving_drain_readyz_flips_and_sheds_retriable():
    from mxnet_tpu.serving import Draining
    srv = _server(name="drain-a")
    try:
        queued = srv.submit(np.ones((2, 3), np.float32))
        assert srv.readyz()["ready"]
        signal_runtime().deliver(int(_signal.SIGTERM))
        rz = srv.readyz()
        assert not rz["ready"]              # flips false IMMEDIATELY
        assert any("draining" in r for r in rz["reasons"])
        with pytest.raises(Draining) as err:
            srv.submit(np.ones((2, 3), np.float32))
        assert err.value.retriable          # clients resubmit elsewhere
        assert isinstance(err.value, mx.base.MXNetError)
        # the in-flight (queued) request still completes within its
        # deadline — drain finishes work, then closes
        srv.drain()
        outs = srv.result(queued)
        assert np.allclose(outs[0], [3.0, 3.0])
        assert srv._closed
        st = srv.stats()
        assert st["drain_signals"] == 1 and st["drained_rejects"] == 1
        assert st["completed"] == 1
    finally:
        srv.close()


def test_serving_second_signal_closes_immediately():
    from mxnet_tpu.serving import ServerClosed
    srv = _server(name="drain-b")
    signal_runtime().deliver(int(_signal.SIGTERM))
    signal_runtime().deliver(int(_signal.SIGTERM))
    assert srv._closed
    with pytest.raises(ServerClosed):
        srv.submit(np.ones((1, 3), np.float32))


# -- resolve() ---------------------------------------------------------------

def test_resolve_env_arming(monkeypatch):
    from mxnet_tpu.resilience.supervisor import resolve
    assert resolve(None) is None
    assert isinstance(resolve(True), TrainingSupervisor)
    sup = _sup()
    assert resolve(sup) is sup
    monkeypatch.setenv("MXTPU_SUPERVISOR", "1")
    assert isinstance(resolve(None), TrainingSupervisor)
