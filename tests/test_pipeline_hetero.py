"""Heterogeneous 1F1B pipeline: ragged stages + BatchNorm aux + rng ops.

VERDICT r3 weak #1 / next #3: the SPMD pipeline previously rejected aux
states, rng ops, and non-isomorphic stages — so ResNet-50 (the repo's
flagship) could not be staged, while the reference's ctx_group placement
split any graph (graph_executor.cc:386-398). These tests pin the
generalized machinery (parallel/pipeline_hetero.py):

* exactness of the 1F1B schedule against ``reference_step`` — the
  sequential-microbatch oracle with identical key folding and aux
  chaining — for a ragged MLP with BatchNorm AND Dropout;
* inference parity against the plain executor;
* ResNet-50 staged by ``pipe_stages=4`` ctx_group annotations training
  one exact 1F1B step (loss + every grad + every aux) on the virtual
  mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.pipeline import pipeline_from_symbol


def _ragged_bn_dropout_symbol(d_in, widths, n_classes):
    data = mx.sym.var("data")
    h = data
    with mx.AttrScope(ctx_group="prologue"):
        h = mx.sym.FullyConnected(h, name="embed", num_hidden=widths[0],
                                  flatten=False)
    for i, w in enumerate(widths):
        with mx.AttrScope(ctx_group=f"stage{i}"):
            h = mx.sym.FullyConnected(h, name=f"fc{i}", num_hidden=w,
                                      flatten=False)
            h = mx.sym.BatchNorm(h, name=f"bn{i}", axis=-1,
                                 fix_gamma=False, momentum=0.8)
            h = mx.sym.Activation(h, act_type="relu", name=f"act{i}")
            if i == 1:
                h = mx.sym.Dropout(h, p=0.4, name="drop1")
    with mx.AttrScope(ctx_group="epilogue"):
        h = mx.sym.FullyConnected(h, name="head", num_hidden=n_classes,
                                  flatten=False)
        return mx.sym.SoftmaxOutput(h, name="softmax")


def _init_ragged(widths, d_in, n_classes, rng):
    args, auxs = {}, {}
    pairs = [("embed", d_in, widths[0])]
    pv = widths[0]
    for i, w in enumerate(widths):
        pairs.append((f"fc{i}", pv, w))
        pv = w
    pairs.append(("head", pv, n_classes))
    for nm, a, b in pairs:
        args[f"{nm}_weight"] = jnp.asarray(
            rng.normal(0, .4, (b, a)).astype(np.float32))
        args[f"{nm}_bias"] = jnp.asarray(
            rng.normal(0, .1, (b,)).astype(np.float32))
    for i, w in enumerate(widths):
        args[f"bn{i}_gamma"] = jnp.asarray(
            1 + 0.1 * rng.randn(w).astype(np.float32))
        args[f"bn{i}_beta"] = jnp.asarray(
            0.1 * rng.randn(w).astype(np.float32))
        auxs[f"bn{i}_moving_mean"] = jnp.asarray(
            0.05 * rng.randn(w).astype(np.float32))
        auxs[f"bn{i}_moving_var"] = jnp.asarray(
            1 + 0.05 * rng.randn(w).astype(np.float32))
    return args, auxs


def test_hetero_1f1b_exact_vs_sequential_reference():
    """Ragged widths + BN aux + Dropout rng: the pipelined step must
    reproduce the sequential-microbatch semantics bit-for-bit (same key
    folding) — loss, every gradient, every aux update."""
    d_in, widths = 16, [16, 24, 24, 12]
    out = _ragged_bn_dropout_symbol(d_in, widths, 5)
    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    apply_fn = pipeline_from_symbol(out, mesh, n_microbatches=4)
    # delegation happened: the hetero path exposes the oracle
    assert hasattr(apply_fn, "reference_step")
    assert [len(a) for a in apply_fn.stage_aux_names] == [2, 2, 2, 2]

    rng = np.random.RandomState(0)
    args, auxs = _init_ragged(widths, d_in, 5, rng)
    x = jnp.asarray(rng.normal(0, 1, (8, d_in)).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 5, (8,)).astype(np.float32))
    key = jax.random.PRNGKey(42)

    loss_p, grads_p, aux_p = apply_fn.train_step(args, x, y,
                                                 aux_dict=auxs, rng=key)
    loss_r, grads_r, aux_r = apply_fn.reference_step(args, x, y,
                                                     aux_dict=auxs,
                                                     rng=key)
    assert abs(float(loss_p) - float(loss_r)) < 1e-5
    assert set(grads_p) == set(grads_r)
    for k in sorted(grads_r):
        np.testing.assert_allclose(
            np.asarray(grads_p[k]), np.asarray(grads_r[k]),
            rtol=2e-4, atol=2e-5, err_msg=k)
    assert set(aux_p) == set(aux_r)
    for k in sorted(aux_r):
        np.testing.assert_allclose(
            np.asarray(aux_p[k]), np.asarray(aux_r[k]),
            rtol=1e-5, atol=1e-6, err_msg=k)


def test_hetero_apply_matches_executor_forward():
    d_in, widths = 16, [16, 24, 24, 12]
    out = _ragged_bn_dropout_symbol(d_in, widths, 5)
    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    apply_fn = pipeline_from_symbol(out, mesh, n_microbatches=4)
    rng = np.random.RandomState(1)
    args, auxs = _init_ragged(widths, d_in, 5, rng)
    x = jnp.asarray(rng.normal(0, 1, (8, d_in)).astype(np.float32))

    outv = apply_fn(args, x, aux_dict=auxs)
    ex = out.simple_bind(mx.cpu(), data=(8, d_in), grad_req="null")
    for nme, v in args.items():
        ex.arg_dict[nme][:] = mx.nd.array(np.asarray(v))
    for nme, v in auxs.items():
        ex.aux_dict[nme][:] = mx.nd.array(np.asarray(v))
    ref = ex.forward(is_train=False, data=np.asarray(x))[0].asnumpy()
    np.testing.assert_allclose(np.asarray(outv), ref, rtol=1e-4,
                               atol=1e-5)


def test_resnet50_staged_1f1b_steady_state_exact():
    """The flagship, in 1F1B *steady state*: ResNet-50 staged over pipe=4
    by ctx_group (pipe_stages=4), n_microbatches = 16 = 4x stages — the
    schedule runs well past fill (microbatches >> stages), so a bug that
    only appears after the warm-up ramp (ring-slot reuse, carried-state
    clobbering) cannot pass. One training step exact vs the unpipelined
    sequential reference — 153 parameter grads and 98 BatchNorm aux
    states."""
    sym = models.get_symbol("resnet", num_layers=50, num_classes=10,
                            image_shape="8,8,3", pipe_stages=4)
    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    apply_fn = pipeline_from_symbol(sym, mesh, n_microbatches=16)
    assert hasattr(apply_fn, "reference_step")
    # every residual unit landed in a stage; stem/head outside
    assert sum(len(v) for v in apply_fn.stage_param_names) == 150
    assert sum(len(a) for a in apply_fn.stage_aux_names) == 98

    ex = sym.simple_bind(mx.cpu(), data=(16, 8, 8, 3), grad_req="null")
    args = {k: jnp.asarray(v.asnumpy()) for k, v in ex.arg_dict.items()
            if k not in ("data", "softmax_label")}
    auxs = {k: jnp.asarray(v.asnumpy()) for k, v in ex.aux_dict.items()}
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(16, 8, 8, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, (16,)).astype(np.float32))
    key = jax.random.PRNGKey(1)

    loss_p, grads_p, aux_p = apply_fn.train_step(args, x, y,
                                                 aux_dict=auxs, rng=key)
    loss_r, grads_r, aux_r = apply_fn.reference_step(args, x, y,
                                                     aux_dict=auxs,
                                                     rng=key)
    assert abs(float(loss_p) - float(loss_r)) < 1e-4
    assert set(grads_p) == set(grads_r)
    for k in sorted(grads_r):
        np.testing.assert_allclose(
            np.asarray(grads_p[k]), np.asarray(grads_r[k]),
            rtol=1e-3, atol=1e-5, err_msg=k)
    for k in sorted(aux_r):
        np.testing.assert_allclose(
            np.asarray(aux_p[k]), np.asarray(aux_r[k]),
            rtol=1e-4, atol=1e-6, err_msg=k)


def _ragged_relu_symbol(d_in, widths, n_classes):
    """BN/rng-free ragged pipeline (deterministic compile, for the
    memory-bound test)."""
    data = mx.sym.var("data")
    h = data
    with mx.AttrScope(ctx_group="prologue"):
        h = mx.sym.FullyConnected(h, name="embed", num_hidden=widths[0],
                                  flatten=False)
    for i, w in enumerate(widths):
        with mx.AttrScope(ctx_group=f"stage{i}"):
            h = mx.sym.FullyConnected(h, name=f"fc{i}", num_hidden=w,
                                      flatten=False)
            h = mx.sym.Activation(h, act_type="relu", name=f"act{i}")
    with mx.AttrScope(ctx_group="epilogue"):
        h = mx.sym.FullyConnected(h, name="head", num_hidden=n_classes,
                                  flatten=False)
        return mx.sym.SoftmaxOutput(h, name="softmax")


def test_hetero_1f1b_activation_ring_memory_bound():
    """The 1F1B memory claim, asserted on compiled buffers: saved
    activations live in a ring of 2*n_stages slots, so compile-time temp
    memory must NOT grow with the number of microbatches beyond the
    per-microbatch I/O buffers (pipeline input, its gradient, and the
    prologue staging — ~3 flat activation buffers per microbatch). A
    schedule that retained per-microbatch activations for backward (the
    GPipe failure mode) would grow by at least the stage-internal
    activation footprint per microbatch and fail the slope bound."""
    d_in, widths = 256, [512, 384, 512, 256]
    out = _ragged_relu_symbol(d_in, widths, 5)
    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    apply_fn = pipeline_from_symbol(out, mesh)
    rng = np.random.RandomState(0)
    args, prev = {}, widths[0]
    pairs = [("embed", d_in, widths[0])]
    for i, w in enumerate(widths):
        pairs.append((f"fc{i}", prev, w))
        prev = w
    pairs.append(("head", prev, 5))
    for nm, a, b in pairs:
        args[f"{nm}_weight"] = jnp.asarray(
            rng.normal(0, .1, (b, a)).astype(np.float32))
        args[f"{nm}_bias"] = jnp.zeros((b,), jnp.float32)

    mb = 32                      # fixed microbatch SIZE
    l_act_bytes = mb * max(widths) * 4   # one flat activation buffer

    def temp_bytes(n_micro):
        x = jnp.zeros((mb * n_micro, d_in), jnp.float32)
        y = jnp.zeros((mb * n_micro,), jnp.float32)
        f = jax.jit(lambda a, x, y: apply_fn.train_step(
            a, x, y, n_microbatches=n_micro, rng=jax.random.PRNGKey(0)))
        return f.lower(args, x, y).compile() \
            .memory_analysis().temp_size_in_bytes

    t8, t32 = temp_bytes(8), temp_bytes(32)
    slope = (t32 - t8) / 24.0    # bytes per extra microbatch
    # I/O buffers cost ~3 L_act per microbatch; GPipe-style retention
    # would cost >= stage-count * L_act more on top (here >= 4 L_act)
    assert slope <= 3.5 * l_act_bytes, (slope, l_act_bytes)
    # and in absolute terms the schedule's working set is flat: 4x the
    # microbatch count grows temp memory by well under 2x
    assert t32 < 1.5 * t8, (t8, t32)
