"""rcnn example package: dataset / loader / eval units.

Reference analogue: the reference ships rcnn/ as an importable package
(dataset/imdb.py, core/loader.py, dataset/pascal_voc_eval.py); these
tests pin the same contracts on our examples/rcnn modules without
running full training (the training gates live in test_examples.py).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "rcnn"))

from dataset import ImageDB, PascalVOC, SyntheticShapes  # noqa: E402
from eval import class_ap, evaluate_detections, proposal_recall  # noqa: E402


def test_synthetic_db_reproducible():
    db = SyntheticShapes(8, seed=4)
    img1, gt1 = db.sample(3)
    img2, gt2 = db.sample(3)
    np.testing.assert_array_equal(img1, img2)
    np.testing.assert_array_equal(gt1, gt2)
    assert img1.shape == (3, 64, 64) and gt1.shape[1] == 5
    assert 0.0 <= img1.min() and img1.max() <= 1.0


def test_flipped_db_mirrors_boxes():
    db = SyntheticShapes(4, seed=9)
    aug = db.append_flipped()
    assert len(aug) == 2 * len(db)
    img, gt = db.sample(1)
    fimg, fgt = aug.sample(1 + len(db))
    np.testing.assert_array_equal(fimg, img[..., ::-1])
    if len(gt):
        w = img.shape[-1]
        np.testing.assert_allclose(fgt[:, 1], w - 1 - gt[:, 3])
        np.testing.assert_allclose(fgt[:, 3], w - 1 - gt[:, 1])
        np.testing.assert_array_equal(fgt[:, 0], gt[:, 0])
        np.testing.assert_array_equal(fgt[:, [2, 4]], gt[:, [2, 4]])


def _write_voc_fixture(root):
    """Minimal VOCdevkit: 2 images, XML annotations, trainval listing."""
    from mxnet_tpu import image as mx_image
    voc = os.path.join(root, "VOC2007")
    for sub in ("JPEGImages", "Annotations",
                os.path.join("ImageSets", "Main")):
        os.makedirs(os.path.join(voc, sub), exist_ok=True)
    rng = np.random.RandomState(0)
    names = ["000001", "000007"]
    boxes = {"000001": [("dog", 10, 12, 40, 44), ("person", 2, 2, 20, 30)],
             "000007": [("car", 5, 8, 50, 58)]}
    for stem in names:
        arr = (rng.rand(64, 64, 3) * 255).astype(np.uint8)
        mx_image.imwrite(os.path.join(voc, "JPEGImages", f"{stem}.jpg"),
                         arr)
        objs = "".join(
            f"<object><name>{n}</name><difficult>0</difficult><bndbox>"
            f"<xmin>{x1 + 1}</xmin><ymin>{y1 + 1}</ymin>"
            f"<xmax>{x2 + 1}</xmax><ymax>{y2 + 1}</ymax>"
            "</bndbox></object>"
            for n, x1, y1, x2, y2 in boxes[stem])
        with open(os.path.join(voc, "Annotations", f"{stem}.xml"),
                  "w") as f:
            f.write(f"<annotation><filename>{stem}.jpg</filename>"
                    f"<size><width>64</width><height>64</height>"
                    f"<depth>3</depth></size>{objs}</annotation>")
    with open(os.path.join(voc, "ImageSets", "Main", "trainval.txt"),
              "w") as f:
        f.write("\n".join(names) + "\n")
    return root


def test_pascal_voc_reader(tmp_path):
    root = _write_voc_fixture(str(tmp_path))
    db = PascalVOC(root, image_set="trainval", year="2007")
    assert len(db) == 2
    img, gt = db.sample(0)
    assert img.shape[0] == 3 and img.dtype == np.float32
    assert img.max() <= 1.0
    # dog + person, 1-based xml corners converted to 0-based
    assert {int(r[0]) for r in gt} == \
        {db.classes.index("dog"), db.classes.index("person")}
    dog = gt[[int(r[0]) == db.classes.index("dog") for r in gt]][0]
    np.testing.assert_allclose(dog[1:5], [10, 12, 40, 44])
    # roidb materialises annotations without decoding images
    roidb = db.roidb()
    assert len(roidb) == 2 and roidb[1]["gt"].shape == (1, 5)


def test_anchor_loader_contract():
    from loader import AnchorLoader
    db = SyntheticShapes(8, seed=2)
    it = AnchorLoader(db, batch_size=4, im_size=64, stride=8,
                      scales=(2.0, 3.0, 4.0), ratios=(0.5, 1.0, 2.0),
                      rpn_batch=32, max_gt=6, seed=3)
    batches = list(it)
    assert len(batches) == 2
    b = batches[0]
    shapes = [d.shape for d in b.data]
    n_anchor = (64 // 8) ** 2 * 9
    assert shapes == [(4, 3, 64, 64), (4, 3), (4, 6, 5)]
    assert [l.shape for l in b.label] == \
        [(4, n_anchor), (4, n_anchor, 4), (4, n_anchor, 1)]
    lab = b.label[0].asnumpy()
    # labels in {-1, 0, 1}; the sampled rpn batch is bounded
    assert set(np.unique(lab)) <= {-1.0, 0.0, 1.0}
    assert ((lab >= 0).sum(axis=1) <= 32).all()
    # fg anchors carry weighted targets
    wgt = b.label[2].asnumpy()
    assert (wgt[lab == 1] == 1.0).all()
    # padded gt unpads to ragged rows
    ragged = AnchorLoader.unpad_gt(b.data[2].asnumpy())
    assert all(r.shape[1] == 5 and (r[:, 0] >= 0).all() for r in ragged)
    # epoch 2 after reset
    it.reset()
    assert len(list(it)) == 2


def test_eval_per_class_and_recall():
    # one image, two classes; class 0 detected correctly, class 1 missed
    gts = [[[0, 10, 10, 20, 20], [1, 40, 40, 50, 50]]]
    dets = [[[0, 0.9, 10, 10, 20, 20], [0, 0.3, 0, 0, 5, 5]]]
    ap0, n_gt0, n_det0 = class_ap(dets, gts, 0)
    ap1, _, _ = class_ap(dets, gts, 1)
    assert ap0 == pytest.approx(1.0) and n_gt0 == 1 and n_det0 == 2
    assert ap1 == 0.0
    lines = []
    m = evaluate_detections(dets, gts, ("a", "b"), log=lines.append)
    assert m == pytest.approx(0.5)
    assert any("mAP" in ln for ln in lines)
    rec = proposal_recall([[[10, 10, 20, 20]]], gts)
    assert rec == pytest.approx(0.5)


def test_bbox_norm_roundtrip_and_stats():
    """Per-class BboxNorm (VERDICT r4 #6): estimated statistics are
    finite with positive stds, normalize/denormalize round-trips, and
    the default instance reproduces the fixed BBOX_STDS behavior."""
    from dataset import SyntheticShapes
    from rcnn_common import (BBOX_STDS, BboxNorm, encode_boxes,
                             estimate_bbox_stats)

    db = SyntheticShapes(16, im_size=64, seed=3)
    norm = estimate_bbox_stats(db, 3, n_images=16,
                               rng=np.random.RandomState(0))
    assert norm.stds.shape == (4, 4) and norm.means.shape == (4, 4)
    assert np.isfinite(norm.means).all()
    assert (norm.stds[1:] > 0).all()
    d = np.array([0.05, -0.1, 0.2, -0.03], np.float32)
    for cls in range(1, 4):
        back = norm.denormalize(cls, norm.normalize(cls, d))
        np.testing.assert_allclose(back, d, rtol=1e-5, atol=1e-6)
    # default = the historical constants
    default = BboxNorm(3)
    np.testing.assert_allclose(default.normalize(2, d), d / BBOX_STDS)
    # save/load round trip
    import io as _io
    buf = _io.BytesIO()
    norm.save(buf)
    buf.seek(0)
    loaded = BboxNorm.load(buf)
    np.testing.assert_array_equal(loaded.stds, norm.stds)
    np.testing.assert_array_equal(loaded.means, norm.means)


def test_assign_anchor_targets_honors_im_info():
    """Rectangular valid extent: anchors beyond the im_info bounds are
    never labeled (the padded-input contract, reference assign_anchor)."""
    from model import FEAT, RATIOS, SCALES, STRIDE
    from rcnn_common import assign_anchor_targets, make_anchor_grid

    anchors = make_anchor_grid(FEAT, FEAT, STRIDE, SCALES, RATIOS)
    gt = np.array([[0, 4, 4, 28, 28]], np.float32)
    rng = np.random.RandomState(0)
    lab, _, _ = assign_anchor_targets(anchors, gt, 64, rng=rng,
                                      im_info=(40, 40, 1.0))
    outside = ((anchors[:, 2] >= 40) | (anchors[:, 3] >= 40)
               | (anchors[:, 0] < 0) | (anchors[:, 1] < 0))
    assert (lab[outside] == -1).all()
    assert (lab == 1).any()


def test_detect_maps_boxes_back_to_source_coords():
    """im_info scale path: a 2x-sized scene goes through prepare_image
    and detections come back in SOURCE pixel coordinates (reference
    tester.py pred_boxes /= im_scale)."""
    from dataset import SyntheticShapes
    from model import IMG, RCNN, prepare_image, detect

    img128, _ = SyntheticShapes(1, im_size=2 * IMG, seed=12).sample(0)
    padded, info = prepare_image(img128)
    assert padded.shape == (3, IMG, IMG)
    assert info[2] == 0.5 and info[0] == IMG and info[1] == IMG
    net = RCNN()  # untrained: only the coordinate contract is checked
    dets = detect(net, img128, score_thresh=0.0)
    for d in dets:
        x1, y1, x2, y2 = d[2:6]
        assert 0 <= x1 <= 2 * IMG - 1 and 0 <= y2 <= 2 * IMG - 1


def test_train_step_ohem_and_scale_jitter_mechanics():
    """OHEM head sampling + per-image im_info training: one step with
    both options produces finite losses and updates parameters."""
    import mxnet_tpu as mx
    from dataset import SyntheticShapes
    from model import (IMG, FEAT, RATIOS, SCALES, STRIDE, RCNN,
                       default_im_info, prepare_image, train_step)
    from rcnn_common import make_anchor_grid

    mx.random.seed(11)
    rng = np.random.RandomState(4)
    net = RCNN()
    trainer = mx.gluon.Trainer(net.params(), "sgd",
                               {"learning_rate": 0.05})
    anchors = make_anchor_grid(FEAT, FEAT, STRIDE, SCALES, RATIOS)
    db = SyntheticShapes(2, im_size=80, seed=5)
    imgs, gts, infos = [], [], []
    for i in range(2):
        img, gt = db.sample(i)
        prepped, info = prepare_image(img)
        g = gt.copy()
        if len(g):
            g[:, 1:5] = g[:, 1:5] * info[2]
        imgs.append(prepped)
        gts.append(g)
        infos.append(info)
    # first step materializes gluon's deferred-init parameters
    losses = train_step(net, trainer, np.stack(imgs), gts, anchors,
                        default_im_info(), rng, im_infos=infos, ohem=True)
    assert all(np.isfinite(v) for v in losses), losses
    before = {k: p.data().asnumpy().copy()
              for k, p in net.params("rpn").items()}
    losses = train_step(net, trainer, np.stack(imgs), gts, anchors,
                        default_im_info(), rng, im_infos=infos, ohem=True)
    assert all(np.isfinite(v) for v in losses), losses
    after = {k: p.data().asnumpy() for k, p in net.params("rpn").items()}
    assert any(not np.allclose(before[k], after[k]) for k in before)
