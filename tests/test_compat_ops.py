"""Compatibility op tail: module-level arithmetic helpers, legacy *_v1
aliases, WarpCTC, slice-assign ops, cv imaging ops, sparse conveniences.

Reference analogues: python/mxnet/ndarray.py module functions,
plugin/warpctc, src/operator/tensor/matrix_op.cc (_slice_assign),
src/io/image_io.cc (_cvimresize et al.).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_nd_arith_helpers_array_array():
    a = mx.nd.array(np.array([[1., 2.], [3., 4.]], np.float32))
    b = mx.nd.array(np.array([[4., 3.], [2., 1.]], np.float32))
    an, bn = a.asnumpy(), b.asnumpy()
    np.testing.assert_allclose(mx.nd.add(a, b).asnumpy(), an + bn)
    np.testing.assert_allclose(mx.nd.subtract(a, b).asnumpy(), an - bn)
    np.testing.assert_allclose(mx.nd.multiply(a, b).asnumpy(), an * bn)
    np.testing.assert_allclose(mx.nd.divide(a, b).asnumpy(), an / bn)
    np.testing.assert_allclose(mx.nd.modulo(a, b).asnumpy(), an % bn)
    np.testing.assert_allclose(mx.nd.power(a, b).asnumpy(), an ** bn,
                               rtol=1e-5)
    np.testing.assert_allclose(mx.nd.maximum(a, b).asnumpy(),
                               np.maximum(an, bn))
    np.testing.assert_allclose(mx.nd.minimum(a, b).asnumpy(),
                               np.minimum(an, bn))
    np.testing.assert_allclose(mx.nd.equal(a, b).asnumpy(),
                               (an == bn).astype(np.float32))
    np.testing.assert_allclose(mx.nd.not_equal(a, b).asnumpy(),
                               (an != bn).astype(np.float32))
    np.testing.assert_allclose(mx.nd.greater(a, b).asnumpy(),
                               (an > bn).astype(np.float32))
    np.testing.assert_allclose(mx.nd.lesser_equal(a, b).asnumpy(),
                               (an <= bn).astype(np.float32))
    assert mx.nd.true_divide is mx.nd.divide


def test_nd_arith_helpers_scalar_dispatch():
    a = mx.nd.array(np.array([1., 2., 3.], np.float32))
    an = a.asnumpy()
    np.testing.assert_allclose(mx.nd.subtract(1.0, a).asnumpy(), 1.0 - an)
    np.testing.assert_allclose(mx.nd.divide(6.0, a).asnumpy(), 6.0 / an)
    np.testing.assert_allclose(mx.nd.power(2.0, a).asnumpy(), 2.0 ** an,
                               rtol=1e-5)
    np.testing.assert_allclose(mx.nd.maximum(a, 2.0).asnumpy(),
                               np.maximum(an, 2.0))
    np.testing.assert_allclose(mx.nd.maximum(2.0, a).asnumpy(),
                               np.maximum(an, 2.0))
    np.testing.assert_allclose(mx.nd.greater(2.0, a).asnumpy(),
                               (2.0 > an).astype(np.float32))
    np.testing.assert_allclose(mx.nd.lesser(2.0, a).asnumpy(),
                               (2.0 < an).astype(np.float32))
    # scalar·scalar degenerates to python numbers
    assert mx.nd.add(2, 3) == 5
    assert mx.nd.maximum(2, 3) == 3
    assert mx.nd.equal(2, 2) == 1.0


def test_sym_helpers():
    x = mx.sym.var("x")
    y = mx.sym.var("y")
    ex = mx.sym.pow(x, y).simple_bind(mx.cpu(), x=(2,), y=(2,))
    ex.arg_dict["x"][:] = mx.nd.array(np.array([2., 3.], np.float32))
    ex.arg_dict["y"][:] = mx.nd.array(np.array([3., 2.], np.float32))
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [8., 9.],
                               rtol=1e-5)
    ex = mx.sym.hypot(x, 4.0).simple_bind(mx.cpu(), x=(1,))
    ex.arg_dict["x"][:] = mx.nd.array(np.array([3.], np.float32))
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [5.], rtol=1e-5)
    assert mx.sym.pow(2, 3) == 8
    full = mx.sym.full((2, 2), 3.5)
    np.testing.assert_allclose(
        full.simple_bind(mx.cpu()).forward()[0].asnumpy(),
        np.full((2, 2), 3.5))


def test_v1_aliases_run():
    data = mx.sym.var("data")
    out = mx.sym.Pooling_v1(data, kernel=(2, 2), stride=(2, 2),
                            pool_type="max")
    ex = out.simple_bind(mx.cpu(), data=(1, 1, 4, 4))
    ex.arg_dict["data"][:] = mx.nd.array(
        np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    res = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(res.ravel(), [5., 7., 13., 15.])
    assert hasattr(mx.nd, "BatchNorm_v1")
    assert hasattr(mx.nd, "Convolution_v1")


def test_no_gradient_and_cross_device_copy():
    x = mx.nd.array(np.array([1., 2.], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd._NoGradient(x) * 3 + x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1., 1.])
    np.testing.assert_allclose(mx.nd._CrossDeviceCopy(x).asnumpy(),
                               x.asnumpy())


def test_slice_assign_ops():
    x = mx.nd.zeros((4, 4))
    y = mx.nd._slice_assign(x, mx.nd.ones((2, 2)), begin=(1, 1), end=(3, 3))
    expect = np.zeros((4, 4), np.float32)
    expect[1:3, 1:3] = 1
    np.testing.assert_allclose(y.asnumpy(), expect)
    z = mx.nd._crop_assign_scalar(x, scalar=7.0, begin=(0, 0), end=(1, 4))
    assert z.asnumpy()[0].sum() == 28
    # gradient flows to both lhs (outside region) and rhs (inside)
    lhs = mx.nd.ones((3, 3))
    rhs = mx.nd.ones((1, 3))
    lhs.attach_grad()
    rhs.attach_grad()
    with mx.autograd.record():
        out = mx.nd._slice_assign(lhs, rhs, begin=(0, 0), end=(1, 3))
    out.backward()
    np.testing.assert_allclose(rhs.grad.asnumpy(), np.ones((1, 3)))
    g = lhs.grad.asnumpy()
    np.testing.assert_allclose(g[0], np.zeros(3))
    np.testing.assert_allclose(g[1:], np.ones((2, 3)))


def test_identity_with_attr_like_rhs():
    a = mx.nd.array(np.array([1., 2.], np.float32))
    b = mx.nd.zeros((2,))
    np.testing.assert_allclose(
        mx.nd._identity_with_attr_like_rhs(a, b).asnumpy(), a.asnumpy())


def test_warpctc_forward_softmax_and_grad():
    T, N, C, L = 6, 2, 5, 3
    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.randn(T * N, C).astype(np.float32))
    label = mx.nd.array(np.array([1, 2, 0, 3, 1, 0], np.float32))
    out = mx.nd.WarpCTC(data, label, label_length=L, input_length=T)
    np.testing.assert_allclose(out.asnumpy().sum(1), np.ones(T * N),
                               rtol=1e-5)
    d = mx.nd.array(rng.randn(T * N, C).astype(np.float32))
    d.attach_grad()
    with mx.autograd.record():
        o = mx.nd.WarpCTC(d, label, label_length=L, input_length=T)
    o.backward()
    g = d.grad.asnumpy()
    assert g.shape == (T * N, C)
    assert np.abs(g).sum() > 0
    # CTC gradient sums to ~0 per row for rows with mass on real labels
    assert np.abs(g.sum(1)).max() < 1e-3


def test_warpctc_trains_down():
    # a tiny repeat-label task: loss should decrease under SGD on the grads
    T, N, C, L = 8, 4, 4, 2
    rng = np.random.RandomState(1)
    w = mx.nd.array(rng.normal(0, 0.1, (T * N, C)).astype(np.float32))
    label = mx.nd.array(
        np.tile(np.array([1, 2], np.float32), N))

    def loss_of(dat):
        import jax.numpy as jnp
        from mxnet_tpu.ops.contrib_ops import _ctc_forward
        import jax
        logp = jax.nn.log_softmax(
            np.asarray(dat.asnumpy(), np.float32).reshape(T, N, C), axis=-1)
        logp = np.transpose(logp, (1, 0, 2))
        lab = label.asnumpy().reshape(N, L).astype(np.int32)
        dl = np.full((N,), T, np.int32)
        ll = (lab != 0).sum(1).astype(np.int32)
        return float(np.sum(jax.vmap(_ctc_forward)(
            jnp.asarray(logp), jnp.asarray(lab), jnp.asarray(dl),
            jnp.asarray(ll))))

    first = loss_of(w)
    for _ in range(10):
        w.attach_grad()
        with mx.autograd.record():
            out = mx.nd.WarpCTC(w, label, label_length=L, input_length=T)
        out.backward()
        w = mx.nd.array(w.asnumpy() - 1.0 * w.grad.asnumpy())
    assert loss_of(w) < first


def test_cv_ops():
    img = mx.nd.array(
        (np.random.RandomState(0).rand(8, 6, 3) * 255).astype(np.uint8))
    r = mx.nd._cvimresize(img, w=12, h=16)
    assert r.shape == (16, 12, 3) and r.dtype == np.uint8
    r2 = mx.image.imresize(img, 3, 4, interp=0)
    assert r2.shape == (4, 3, 3)
    p = mx.nd._cvcopyMakeBorder(img, top=2, bot=1, left=3, right=0,
                                type=0, value=9.0)
    assert p.shape == (11, 9, 3)
    assert (p.asnumpy()[:2] == 9).all()
    pe = mx.image.copyMakeBorder(img, 1, 1, 1, 1, border_type=1)
    np.testing.assert_array_equal(pe.asnumpy()[0, 1:-1], img.asnumpy()[0])


def test_cv_decode_roundtrip():
    cv2 = pytest.importorskip("cv2")
    img = (np.random.RandomState(0).rand(10, 8, 3) * 255).astype(np.uint8)
    ok, enc = cv2.imencode(".png", img)
    assert ok
    d = mx.nd._cvimdecode(enc.tobytes())
    assert d.shape == (10, 8, 3)
    # png is lossless; BGR->RGB flip relative to raw cv2
    np.testing.assert_array_equal(d.asnumpy(), img[:, :, ::-1])


def test_sparse_conveniences():
    dense = mx.nd.array(np.array([[0, 1], [0, 0], [2, 0]], np.float32))
    rsp = mx.nd.cast_storage(dense, "row_sparse")
    assert rsp.stype == "row_sparse"
    back = mx.nd.cast_storage(rsp, "default")
    np.testing.assert_allclose(back.asnumpy(), dense.asnumpy())
    ret = mx.nd.sparse_retain(rsp, mx.nd.array(np.array([0], np.float32)))
    np.testing.assert_allclose(ret.tostype("default").asnumpy(),
                               [[0, 1], [0, 0], [0, 0]])
    with pytest.raises(mx.MXNetError):
        mx.nd.sparse_retain(dense, mx.nd.array(np.array([0], np.float32)))


def test_positional_parameters_after_inputs():
    # reference codegen signatures fill declared params positionally:
    # clip(data, a_min, a_max), expand_dims(data, axis), ...
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(mx.nd.clip(x, 1.0, 4.0).asnumpy(),
                               np.clip(x.asnumpy(), 1, 4))
    assert mx.nd.expand_dims(x, 1).shape == (2, 1, 3)
    np.testing.assert_allclose(mx.nd.slice_axis(x, 1, 0, 2).asnumpy(),
                               x.asnumpy()[:, :2])
    assert mx.nd.transpose(x, (1, 0)).shape == (3, 2)
    # mixed positional + keyword
    np.testing.assert_allclose(mx.nd.clip(x, 1.0, a_max=4.0).asnumpy(),
                               np.clip(x.asnumpy(), 1, 4))
    # symbolic namespace too
    s = mx.sym.clip(mx.sym.var("a"), 0.0, 1.0)
    ex = s.simple_bind(mx.cpu(), a=(2,))
    ex.arg_dict["a"][:] = mx.nd.array(np.array([-1.0, 2.0], np.float32))
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [0.0, 1.0])
    # too many positionals still errors
    with pytest.raises(mx.MXNetError):
        mx.nd.clip(x, 1.0, 4.0, 9.0)
