"""The numeric-gradient harness itself (reference: every op test in
tests/python/unittest/test_operator.py leans on test_utils; SURVEY.md §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


def test_assert_almost_equal():
    tu.assert_almost_equal(np.ones(3), np.ones(3))
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(np.ones(3), np.ones(3) + 0.1)


def test_check_numeric_gradient_fc():
    data = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    tu.check_numeric_gradient(
        s, {"data": np.random.rand(3, 5), "fc_weight": np.random.rand(4, 5),
            "fc_bias": np.random.rand(4)})


@pytest.mark.parametrize("op,dfdx", [
    ("sqrt", lambda x: 0.5 / np.sqrt(x)),
    ("exp", np.exp),
    ("log", lambda x: 1.0 / x),
    ("sigmoid", lambda x: (1 / (1 + np.exp(-x))) * (1 - 1 / (1 + np.exp(-x)))),
    ("tanh", lambda x: 1 - np.tanh(x) ** 2),
])
def test_check_numeric_gradient_unary(op, dfdx):
    data = mx.sym.Variable("data")
    s = getattr(mx.sym, op)(data)
    x = np.random.rand(4, 3) + 0.5
    tu.check_numeric_gradient(s, {"data": x})
    og = np.random.rand(4, 3)
    tu.check_symbolic_backward(s, {"data": x}, [og], [og * dfdx(x)],
                               rtol=1e-4, atol=1e-5)


def test_check_symbolic_forward():
    data = mx.sym.Variable("data")
    x = np.array([4.0, 9.0], dtype=np.float32)
    tu.check_symbolic_forward(mx.sym.sqrt(data), {"data": x},
                              [np.sqrt(x)])


def test_check_consistency_dtypes():
    data = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    tu.check_consistency(
        s, [{"ctx": mx.cpu(0), "data": (4, 6)},
            {"ctx": mx.cpu(1), "data": (4, 6),
             "type_dict": {"data": "float64"}}])


def test_np_reduce():
    x = np.random.rand(3, 4, 5)
    assert tu.np_reduce(x, (0, 2), True, np.sum).shape == (1, 4, 1)
    tu.assert_almost_equal(tu.np_reduce(x, 1, False, np.max),
                           x.max(axis=1), rtol=1e-6, atol=1e-6)


def test_rand_shapes():
    assert len(tu.rand_shape_2d()) == 2
    assert len(tu.rand_shape_3d()) == 3
    assert len(tu.rand_shape_nd(5)) == 5


def test_simple_forward():
    data = mx.sym.Variable("data")
    out = tu.simple_forward(mx.sym.relu(data),
                            data=np.array([-1.0, 2.0], dtype=np.float32))
    tu.assert_almost_equal(out, np.array([0.0, 2.0]))


def test_get_mnist_synthetic():
    m = tu.get_mnist()
    assert m["train_data"].shape[1:] == (1, 28, 28)
    assert m["train_data"].shape[0] == m["train_label"].shape[0]
    # learnable: same label -> similar images
    labels = m["train_label"]
    imgs = m["train_data"]
    a = imgs[labels == 3].mean(axis=0)
    b = imgs[labels == 7].mean(axis=0)
    assert np.abs(a - b).max() > 0.5
