"""The low-precision tier (mxnet_tpu/quant, docs/how_to/quantization.md).

Covers: quantize/dequantize formats, the annotate-slot quant signature
(transform_sig + persistent program keys), cross-process bitwise
determinism of the quantized program (golden via a real subprocess),
the accuracy gate's TP/TN + typed-warning fallback, the calibration
sidecar (roundtrip, corrupt/missing/truncated/fault-injected
``quant.sidecar.read`` all fall back to recalibration, never a crash),
DataIter calibration, int8-vs-fp32 coalescer padding bytes, quantized
coalesced serving under ``MXTPU_RETRACE_STRICT=1``, the admission
queue's request-shape histogram, the dynamic loss-scale schedule
(fake grad stream: overflow, recovery, clamps), the
``MXTPU_PRECISION=bf16`` mode through Module/Gluon/SPMD (non-finite
steps skipped bitwise), and ZeRO + bf16 composing bitwise vs
replicated.
"""
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import quant
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io import DataBatch, DataDesc
from mxnet_tpu.quant import (CalibrationStats, LossScaleConfig,
                             QuantAccuracyWarning, QuantConfig, calibrate,
                             load_stats, quantize_backend, save_stats)
from mxnet_tpu.quant import loss_scale as ls_mod

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_disk_cache(tmp_path, monkeypatch):
    """Tests compile into a throwaway cache dir (and never pollute the
    user's) — the cross-process golden overrides deliberately."""
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    yield


def mlp_infer_module(batch=8, in_dim=16, hidden=32, classes=8, seed=3):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=classes, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    mod = mx.mod.Module(net, label_names=[], context=mx.cpu())
    mod.bind(data_shapes=[("data", (batch, in_dim))], label_shapes=None,
             for_training=False)
    mx.random.seed(seed)
    mod.init_params(mx.init.Xavier())
    return mod


def calib_feeds(n=4, batch=8, in_dim=16, seed=0):
    rng = np.random.RandomState(seed)
    return [{"data": rng.rand(batch, in_dim).astype(np.float32)}
            for _ in range(n)]


# ---------------------------------------------------------------------------
# formats + core
# ---------------------------------------------------------------------------

def test_int8_roundtrip_error_bound():
    fmt = quant.FORMATS["int8"]
    rng = np.random.RandomState(0)
    x = rng.randn(64, 32).astype(np.float32)
    scale = quant.scale_for(float(np.max(np.abs(x))), fmt)
    q = quant.quantize(x, scale, fmt)
    assert str(np.asarray(q).dtype) == "int8"
    back = np.asarray(quant.dequantize(q, scale))
    # per-tensor symmetric int8: error bounded by half a step
    assert np.max(np.abs(back - x)) <= float(np.asarray(scale)) * 0.5 + 1e-7
    # zeros stay exact (scale falls back to 1.0)
    z = quant.quantize(np.zeros(4), quant.scale_for(0.0, fmt), fmt)
    assert np.array_equal(np.asarray(z), np.zeros(4, np.int8))


def test_unknown_format_is_typed_error():
    with pytest.raises(MXNetError, match="unknown quantization format"):
        QuantConfig(fmt="int3")


def test_host_and_device_quantize_agree():
    """One scale rule, two implementations (np for weights/clients, jnp
    in-program): integer formats agree bit-for-bit; float formats (fp8)
    to within one representable step — XLA's f32->f8 convert on this
    jax line rounds near-midpoint values differently from ml_dtypes'
    round-to-nearest-even, which is why the HOST quantizer is the
    canonical serving-path one (quantize_host docstring)."""
    rng = np.random.RandomState(2)
    # (16, 8) @ seed 2 contains near-midpoint fp8 cases that expose the
    # rounding divergence — keep it as the regression fixture
    x = rng.randn(16, 8).astype(np.float32)
    for fmt in quant.FORMATS.values():
        scale = quant.host_scale(float(np.max(np.abs(x))), fmt)
        host = quant.quantize_host(x, scale, fmt)
        dev = np.asarray(quant.quantize(x, scale, fmt))
        if np.issubdtype(np.dtype(fmt.dtype), np.integer):
            assert host.tobytes() == dev.tobytes(), fmt.name
        else:
            h, d = host.astype(np.float64), dev.astype(np.float64)
            # adjacent representables at most: e4m3 has 3 mantissa
            # bits, so one grid step is ~|value|/8 for normals
            assert np.all(np.abs(h - d) <= np.abs(h) / 8 + 1e-6), fmt.name


@pytest.mark.skipif("fp8_e4m3" not in quant.FORMATS,
                    reason="jax build has no float8_e4m3fn")
def test_fp8_quantize_keeps_fractional_resolution():
    """fp8 is a FLOAT format: quantize must clip-then-cast onto e4m3's
    own mantissa grid, not round to integers — sub-1.0 scaled values
    survive instead of collapsing to 0."""
    fmt = quant.FORMATS["fp8_e4m3"]
    x = np.asarray([0.3, 0.55, -0.7, 1.25], np.float32)
    q = quant.quantize_host(x, 1.0, fmt)
    back = np.asarray(quant.dequantize(np.asarray(q), 1.0))
    assert np.all(np.abs(back - x) < 0.1), back       # not integerized
    assert np.count_nonzero(back) == 4                # nothing collapsed


def test_input_name_honored_with_quant_on_and_on_fallback():
    """input_name must survive quant=True on the quantized backend AND
    on the gate-refusal fp32 fallback (it names the primary input a
    bare-array submit binds to)."""
    mod = mlp_infer_module()
    qb = mod.as_serving_backend(input_name="data", quant=True,
                                calib_data=calib_feeds())
    assert qb.input_name == "data"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fb = quantize_backend(mod, calib_feeds(), input_name="data",
                              config=QuantConfig(max_accuracy_delta=0.0))
    assert type(fb).__name__ == "ModuleBackend"
    assert fb.input_name == "data"


def test_quant_annotator_stamps_transform_sig():
    from mxnet_tpu import compiler
    from mxnet_tpu.quant.core import quant_scope
    mod = mlp_infer_module()
    shapes = {n: tuple(v.shape)
              for n, v in mod._exec.arg_dict.items()}
    plain = compiler.optimize(mod._symbol, for_training=False,
                              input_shapes=shapes)
    assert "quant=" not in plain.transform_sig
    with quant_scope(QuantConfig(), ["fc1_weight", "fc2_weight"]):
        quanted = compiler.optimize(mod._symbol, for_training=False,
                                    input_shapes=shapes)
    assert "quant=" in quanted.transform_sig
    with quant_scope(QuantConfig(), ["fc1_weight"]):
        partial = compiler.optimize(mod._symbol, for_training=False,
                                    input_shapes=shapes)
    # a different gated parameter set is a different precision decision
    assert partial.transform_sig != quanted.transform_sig


def test_quant_vs_fp32_program_keys_distinct():
    """The persistent cache must never serve a stale-precision program:
    same graph, same avals — different program_key once the quant
    signature joins the transform sig (the sharding_sig pattern)."""
    from mxnet_tpu.compiler import fingerprint as fp
    k_fp32 = fp.program_key("quant-forward", "graphfp", "avals",
                            transform_sig="passes=0;remat=0")
    k_int8 = fp.program_key("quant-forward", "graphfp", "avals",
                            transform_sig="passes=0;remat=0;quant=abc123")
    assert k_fp32 != k_int8


# ---------------------------------------------------------------------------
# calibration + the manifest-covered sidecar
# ---------------------------------------------------------------------------

def test_calibrate_accepts_dataiter_and_dicts():
    rng = np.random.RandomState(1)
    arr = rng.rand(16, 16).astype(np.float32) * 3.0
    it = mx.io.NDArrayIter(arr, batch_size=4)
    stats = calibrate(["data"], it, num_batches=4)
    assert stats.batches == 4
    assert stats.input_absmax["data"] == pytest.approx(
        float(np.max(np.abs(arr))), rel=0.5)
    stats2 = calibrate(["data"], [{"data": arr}])
    assert stats2.input_absmax["data"] == pytest.approx(
        float(np.max(np.abs(arr))))
    with pytest.raises(MXNetError, match="no batches"):
        calibrate(["data"], [])


def test_calibrate_rejects_wrongly_keyed_feeds():
    """Feeds that never carry any named input must raise — silently
    shipping scale-1.0 quantization is the failure mode the docstring
    forbids. A PARTIALLY missing name warns and keeps scale 1.0."""
    with pytest.raises(MXNetError, match="none carried"):
        calibrate(["data"], [{"wrong_key": np.ones((2, 4))}])
    stats = calibrate(["data", "aux_in"],
                      [{"data": np.ones((2, 4)) * 3.0}])
    assert stats.input_absmax["data"] == 3.0
    assert stats.input_absmax["aux_in"] == 0.0


def test_accuracy_gate_not_diluted_by_pad_rows():
    """Calibration batches smaller than the bound batch are zero-padded
    up; the gate must measure the REAL rows only, or the pad rows'
    near-zero error dilutes the delta by padded/real and an
    over-threshold model ships."""
    from mxnet_tpu.quant.ptq import _fit_rows, measure_accuracy_delta

    class _Fixed:
        def __init__(self, row_out):
            self.row_out = row_out

        def infer(self, arrays):
            n = arrays["data"].shape[0]
            out = np.zeros((n, 4), np.float32)
            out[0] = self.row_out          # only row 0 is "real"
            return [out]

    base = _Fixed(np.asarray([1.0, 0, 0, 0], np.float32))
    quantish = _Fixed(np.asarray([2.0, 0, 0, 0], np.float32))
    feed = _fit_rows({"data": np.ones((1, 4), np.float32)}, 32)
    diluted = measure_accuracy_delta(base, quantish, [feed])
    honest = measure_accuracy_delta(base, quantish, [feed],
                                    real_rows=[1])
    # the real row's relative error is 1.0; without row restriction the
    # pad rows cannot hide it here (outputs are zero there), but the
    # restricted measurement must equal the true per-row error exactly
    assert honest["accuracy_delta"] == pytest.approx(1.0)
    assert diluted["accuracy_delta"] == pytest.approx(1.0)

    class _Biased(_Fixed):
        def infer(self, arrays):
            n = arrays["data"].shape[0]
            out = np.ones((n, 4), np.float32)  # bias mass on pad rows
            out[0] = self.row_out
            return [out]

    b2 = _Biased(np.asarray([1.0, 0, 0, 0], np.float32))
    q2 = _Biased(np.asarray([2.0, 0, 0, 0], np.float32))
    diluted = measure_accuracy_delta(b2, q2, [feed])
    honest = measure_accuracy_delta(b2, q2, [feed], real_rows=[1])
    assert honest["accuracy_delta"] == pytest.approx(1.0)
    assert diluted["accuracy_delta"] < 0.05   # the hole the fix closes


def test_sidecar_roundtrip(tmp_path):
    path = str(tmp_path / "calib.json")
    stats = CalibrationStats({"data": 2.5}, batches=3)
    save_stats(stats, path)
    assert os.path.exists(path + ".manifest.json")
    loaded = load_stats(path)
    assert loaded is not None
    assert loaded.to_dict() == stats.to_dict()


def test_sidecar_corrupt_missing_truncated_fall_back(tmp_path):
    """A reloaded Predictor must recalibrate on ANY bad sidecar — flip,
    truncation, missing manifest, absent file — never crash."""
    path = str(tmp_path / "calib.json")
    assert load_stats(path) is None                       # missing
    save_stats(CalibrationStats({"data": 2.5}, 3), path)
    with open(path, "a") as f:                            # flipped bytes
        f.write("garbage")
    assert load_stats(path) is None
    save_stats(CalibrationStats({"data": 2.5}, 3), path)
    with open(path, "w") as f:                            # truncated
        f.write("{")
    assert load_stats(path) is None
    save_stats(CalibrationStats({"data": 2.5}, 3), path)
    os.remove(path + ".manifest.json")                    # manifest gone
    assert load_stats(path) is None


def test_sidecar_read_fault_falls_back_to_recalibration(tmp_path):
    """An injected transient fault at ``quant.sidecar.read`` reads as
    recalibrate — the entry is left in place and the next read works."""
    from mxnet_tpu.resilience import FaultPlan, faults
    path = str(tmp_path / "calib.json")
    save_stats(CalibrationStats({"data": 1.5}, 2), path)
    faults.arm(FaultPlan().arm("quant.sidecar.read", nth=1, count=1,
                               exc="ioerror"))
    try:
        assert load_stats(path) is None          # fault -> recalibrate
        assert faults.stats()["fired"]["quant.sidecar.read"] == 1
        reloaded = load_stats(path)              # entry survived
        assert reloaded is not None and reloaded.batches == 2
    finally:
        faults.disarm()


def test_quantize_backend_reuses_sidecar_without_recalibrating(tmp_path):
    path = str(tmp_path / "calib.json")
    mod = mlp_infer_module()
    feeds = calib_feeds()
    b1 = quantize_backend(mod, feeds, stats_path=path)
    assert b1.quant_report.shipped
    # a second load with DIFFERENT (in-range) batches: recalibration
    # would observe a different absmax; the sidecar hit reuses the
    # first calibration exactly
    other = calib_feeds(seed=99)
    recal = calibrate(["data"], other)
    assert recal.input_absmax != b1.stats.input_absmax
    b2 = quantize_backend(mod, other, stats_path=path)
    assert b2.quant_report.shipped
    assert b2.stats.input_absmax == b1.stats.input_absmax


# ---------------------------------------------------------------------------
# the accuracy gate
# ---------------------------------------------------------------------------

def test_accuracy_gate_ships_good_model():
    mod = mlp_infer_module()
    backend = quantize_backend(mod, calib_feeds())
    assert type(backend).__name__ == "QuantizedModuleBackend"
    rep = backend.quant_report
    assert rep.shipped and rep.accuracy_delta <= rep.threshold
    assert rep.format == "int8" and rep.fallback_reason is None
    assert rep.top1_agreement is not None


def test_accuracy_gate_refuses_and_falls_back_fp32():
    """TP: an impossible threshold refuses the quantized model — the
    fp32 backend ships with the typed QuantAccuracyWarning, and the
    report says why."""
    mod = mlp_infer_module()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        backend = quantize_backend(
            mod, calib_feeds(), config=QuantConfig(max_accuracy_delta=0.0))
    assert type(backend).__name__ == "ModuleBackend"
    assert any(issubclass(w.category, QuantAccuracyWarning)
               for w in caught)
    rep = backend.quant_report
    assert not rep.shipped and "threshold" in rep.fallback_reason


def test_quantized_outputs_close_to_fp32():
    from mxnet_tpu.serving import ModuleBackend
    mod = mlp_infer_module()
    feeds = calib_feeds()
    qb = quantize_backend(mod, feeds)
    base = ModuleBackend(mod)
    base.load()
    b = base.infer(feeds[0])[0]
    q = qb.infer(feeds[0])[0]
    assert np.argmax(b, axis=1).tolist() == np.argmax(q, axis=1).tolist()
    assert float(np.mean(np.abs(b - q))) < 0.02


def test_int8_and_fp32_submissions_identical():
    """A client that pre-quantizes with the published scales and one
    that submits fp32 land in the SAME int8 program — bitwise."""
    mod = mlp_infer_module()
    feeds = calib_feeds()
    qb = quantize_backend(mod, feeds)
    out_f = qb.infer(feeds[0])
    out_q = qb.infer(qb.quantize_inputs(feeds[0]))
    for a, b in zip(out_f, out_q):
        assert np.array_equal(a, b)


def test_embedding_index_inputs_never_quantized():
    """Index-semantic inputs (an Embedding's data slot) must not be
    range-quantized — round(token/scale) destroys the id."""
    data = mx.sym.var("data")
    emb = mx.sym.Embedding(data, input_dim=40, output_dim=8, name="emb")
    fc = mx.sym.FullyConnected(emb, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net, label_names=[], context=mx.cpu())
    mod.bind(data_shapes=[("data", (4,))], label_shapes=None,
             for_training=False)
    mx.random.seed(5)
    mod.init_params(mx.init.Xavier())
    rng = np.random.RandomState(0)
    feeds = [{"data": rng.randint(0, 40, (4,)).astype(np.float32)}
             for _ in range(2)]
    qb = quantize_backend(mod, feeds)
    assert qb.quant_report.shipped
    assert "data" not in qb.quant_report.quantized_inputs
    # the embedding TABLE (a 2-D weight) still quantizes
    assert "emb_weight" in qb.quantized_params


def test_as_serving_backend_knob_and_errors(monkeypatch):
    mod = mlp_infer_module()
    assert type(mod.as_serving_backend()).__name__ == "ModuleBackend"
    with pytest.raises(MXNetError, match="calib_data"):
        mod.as_serving_backend(quant=True)
    monkeypatch.setenv("MXTPU_QUANT", "1")
    with pytest.raises(MXNetError, match="calib_data"):
        mod.as_serving_backend()
    backend = mod.as_serving_backend(calib_data=calib_feeds())
    assert type(backend).__name__ == "QuantizedModuleBackend"
    monkeypatch.setenv("MXTPU_QUANT_MAX_DELTA", "0.0")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fb = mod.as_serving_backend(calib_data=calib_feeds())
    assert type(fb).__name__ == "ModuleBackend"


def test_quantized_backend_from_artifact(tmp_path):
    """Predictor-load quantization: the same symbol-JSON + .params
    artifact surface, with corrupt artifacts keeping their typed
    error."""
    import io as _io
    from mxnet_tpu.quant import quantized_backend_from_artifact
    mod = mlp_infer_module(batch=4)
    arg, aux = mod.get_params()
    buf = _io.BytesIO()
    np.savez(buf, **{f"arg:{k}": v.asnumpy() for k, v in arg.items()},
             **{f"aux:{k}": v.asnumpy() for k, v in aux.items()})
    feeds = calib_feeds(n=2, batch=4)
    qb = quantized_backend_from_artifact(
        mod._symbol.tojson(), buf.getvalue(), (16,), feeds, batch_size=4)
    assert type(qb).__name__ == "QuantizedModuleBackend"
    assert qb.quant_report.shipped
    assert qb.infer(feeds[0])[0].shape == (4, 8)
    with pytest.raises(MXNetError, match="corrupt or truncated"):
        quantized_backend_from_artifact(mod._symbol.tojson(), b"junk",
                                        (16,), feeds, batch_size=4)


# ---------------------------------------------------------------------------
# cross-process determinism (the fingerprint golden)
# ---------------------------------------------------------------------------

_GOLDEN_CHILD = r"""
import hashlib, json, os, sys
import numpy as np
sys.path.insert(0, {root!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import mxnet_tpu as mx
from mxnet_tpu.quant import quantize_backend

data = mx.sym.var("data")
fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
act = mx.sym.Activation(fc1, act_type="relu")
fc2 = mx.sym.FullyConnected(act, num_hidden=8, name="fc2")
net = mx.sym.SoftmaxOutput(fc2, name="softmax")
mod = mx.mod.Module(net, label_names=[], context=mx.cpu())
mod.bind(data_shapes=[("data", (8, 16))], label_shapes=None,
         for_training=False)
mx.random.seed(3)
mod.init_params(mx.init.Xavier())
rng = np.random.RandomState(0)
feeds = [{{"data": rng.rand(8, 16).astype(np.float32)}}
         for _ in range(4)]
qb = quantize_backend(mod, feeds)
h = hashlib.sha256()
for n in sorted(qb._qweights):
    h.update(np.asarray(qb._qweights[n]).tobytes())
    h.update(np.float32(qb._wscales[n]).tobytes())
out = qb.infer(feeds[0])[0]
h.update(np.asarray(out, np.float32).tobytes())
print(json.dumps({{"digest": h.hexdigest(),
                   "sig": qb.program_key_parts()[1]}}))
"""


@pytest.mark.slow
def test_cross_process_quantized_golden(tmp_path):
    """Bitwise determinism across processes: two separate interpreters
    quantize the same seeded model and must agree on the int8 weight
    bytes, the per-tensor scales, the quantized outputs, AND the quant
    program signature — the property that makes the persistent compile
    cache (keyed on that signature) safe to share between processes."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXTPU_COMPILE_CACHE_DIR=str(tmp_path / "cc"))
    script = _GOLDEN_CHILD.format(root=ROOT)
    outs = []
    for _ in range(2):
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True, env=env,
                              timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        outs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    assert outs[0]["digest"] == outs[1]["digest"]
    assert outs[0]["sig"] == outs[1]["sig"]
    assert "quant=" in outs[0]["sig"]


# ---------------------------------------------------------------------------
# serving: padding bytes, strict coalescing, the shape histogram
# ---------------------------------------------------------------------------

def test_int8_padding_bytes_quarter_of_fp32():
    from mxnet_tpu.serving import ShapeBuckets
    buckets = ShapeBuckets([16])
    p8, rows8 = buckets.pad_batch(np.zeros((3, 32, 32, 3), np.int8))
    p32, rows32 = buckets.pad_batch(np.zeros((3, 32, 32, 3), np.float32))
    assert rows8 == rows32 == 3
    assert p8.dtype == np.int8 and p32.dtype == np.float32
    assert p8.nbytes * 4 == p32.nbytes


def test_quantized_serving_coalesced_strict(monkeypatch):
    """The compounding win: int8 requests ride the BatchCoalescer with
    ZERO unwarmed signatures under MXTPU_RETRACE_STRICT=1 (the server
    warmed int8 buckets because the backend declares input_dtypes), and
    per-request scatter equals one batched infer."""
    from mxnet_tpu.serving import InferenceServer
    monkeypatch.setenv("MXTPU_RETRACE_STRICT", "1")
    mod = mlp_infer_module()
    backend = quantize_backend(mod, calib_feeds())
    assert backend.input_dtypes["data"] == "int8"
    server = InferenceServer(backend, name="quant-strict", max_batch=8,
                             workers=0, capacity=64,
                             default_deadline=60.0)
    try:
        server.warm_up()
        rng = np.random.RandomState(7)
        rows = [backend.quantize_inputs(
            {"data": rng.rand(1, 16).astype(np.float32)})
            for _ in range(12)]
        pending = [server.submit(r) for r in rows]
        server.run_pending()
        outs = [server.result(p) for p in pending]
        stats = server.stats()
        assert stats["completed"] == 12
        assert stats["batching"]["unwarmed_dispatch_signatures"] == 0
        assert stats["dispatches"] < 12
        merged = backend.infer(
            {"data": np.concatenate([r["data"] for r in rows])})
        for i, o in enumerate(outs):
            assert np.array_equal(o[0][0], merged[0][i])
    finally:
        server.close()


def test_admission_shape_histogram_records_and_bounds():
    from mxnet_tpu.serving import AdmissionQueue, Deadline, Request
    q = AdmissionQueue(capacity=512)
    for _ in range(3):
        q.offer(Request({"data": np.zeros((1, 16), np.int8)},
                        Deadline(None)))
    q.offer(Request({"data": np.zeros((2, 16), np.float32)},
                    Deadline(None)))
    hist = q.shape_histogram()
    assert hist["1r|data:(16,):int8"] == 3
    assert hist["2r|data:(16,):float32"] == 1
    # bounded: past the cap, new shapes fold into the overflow bucket
    q2 = AdmissionQueue(capacity=8192)
    for i in range(AdmissionQueue._SHAPE_HIST_CAP + 10):
        q2.offer(Request({"data": np.zeros((1, i + 1), np.float32)},
                         Deadline(None)))
    h2 = q2.shape_histogram()
    assert len(h2) <= AdmissionQueue._SHAPE_HIST_CAP + 1
    assert h2[AdmissionQueue._SHAPE_HIST_OVERFLOW] == 10


def test_oversized_requests_reach_the_shape_histogram():
    """Requests rejected as RequestTooLarge never reach the queue, but
    they are exactly the demand signal bucket mining needs — the server
    must record them before raising."""
    from mxnet_tpu.serving import (CallableBackend, InferenceServer,
                                   RequestTooLarge)
    backend = CallableBackend(lambda a: a["data"].sum(axis=1),
                              input_specs={"data": (4,)})
    srv = InferenceServer(backend, name="hist-oversize", buckets=[2],
                          workers=0)
    try:
        srv.warm_up()
        with pytest.raises(RequestTooLarge):
            srv.submit({"data": np.zeros((5, 4), np.float32)})
        hist = srv.stats()["queue"]["shape_histogram"]
        assert hist["5r|data:(4,):float32"] == 1
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# the dynamic loss-scale schedule
# ---------------------------------------------------------------------------

def test_loss_scale_schedule_on_fake_grad_stream():
    """The schedule contract on a scripted stream: grow after a full
    finite streak, back off on overflow, streak resets, clamps hold."""
    import jax.numpy as jnp
    cfg = LossScaleConfig(init_scale=8.0, growth_interval=2,
                          max_scale=32.0, min_scale=2.0)
    state = ls_mod.init_state(cfg)
    stream = ["f", "f",          # full streak -> 16
              "f", "inf",        # overflow   -> 8, streak 0
              "inf",             # again      -> 4
              "f", "f",          # streak     -> 8
              "inf", "inf", "inf", "inf"]  # clamp at min 2
    expected_scale = [8, 16, 16, 8, 4, 4, 8, 4, 2, 2, 2]
    for kind, want in zip(stream, expected_scale):
        grads = {"w": jnp.ones(3) if kind == "f"
                 else jnp.asarray([1.0, np.inf, 1.0])}
        finite = ls_mod.tree_all_finite(grads)
        assert bool(np.asarray(finite)) == (kind == "f")
        state = ls_mod.next_state(state, finite, cfg)
        assert float(np.asarray(state[0])) == want, (kind, want)
    # growth clamps at max_scale
    state = (jnp.float32(32.0), jnp.int32(1))
    state = ls_mod.next_state(state, jnp.bool_(True), cfg)
    assert float(np.asarray(state[0])) == 32.0


def test_host_mirror_matches_functional_schedule():
    import jax.numpy as jnp
    cfg = LossScaleConfig(init_scale=4.0, growth_interval=3,
                          max_scale=64.0, min_scale=1.0)
    host = ls_mod.DynamicLossScale(cfg)
    state = ls_mod.init_state(cfg)
    rng = np.random.RandomState(0)
    for _ in range(40):
        finite = bool(rng.rand() > 0.3)
        host.update(finite)
        state = ls_mod.next_state(state, jnp.bool_(finite), cfg)
        assert float(np.asarray(state[0])) == host.scale


def test_precision_env_resolution(monkeypatch):
    from mxnet_tpu import perf
    monkeypatch.delenv("MXTPU_PRECISION", raising=False)
    assert perf.precision_compute_dtype(None) is None
    assert perf.precision_loss_scale(None) is None
    assert perf.precision_compute_dtype("float16") == "float16"
    monkeypatch.setenv("MXTPU_PRECISION", "bf16")
    assert perf.precision_compute_dtype(None) == "bfloat16"
    assert perf.precision_loss_scale(None) is not None
    assert perf.precision_loss_scale(False) is None
    monkeypatch.setenv("MXTPU_PRECISION", "int7")
    with pytest.raises(MXNetError, match="MXTPU_PRECISION"):
        perf.precision_compute_dtype(None)


# ---------------------------------------------------------------------------
# the MXTPU_PRECISION=bf16 training mode
# ---------------------------------------------------------------------------

def _mlp_train_module(seed=7):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[DataDesc("data", (8, 10))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    mx.random.seed(seed)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    return mod


def _train_batch(rng=None):
    rng = rng or np.random.RandomState(0)
    return DataBatch(
        data=[mx.nd.array(rng.rand(8, 10).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))])


def test_bf16_mode_module_skips_nonfinite_step_bitwise(monkeypatch):
    """MXTPU_PRECISION=bf16 arms the in-program guard in the Module
    fused step: a poison (NaN) batch leaves params BITWISE unchanged,
    backs the scale off, and the next finite step trains normally."""
    from mxnet_tpu import perf
    monkeypatch.setenv("MXTPU_PRECISION", "bf16")
    mod = _mlp_train_module()
    stepper = perf.module_stepper(mod)
    assert stepper is not None
    batch = _train_batch()
    stepper.step(batch)
    stepper.sync_to_module()
    before = {n: v.asnumpy().copy()
              for n, v in mod.get_params()[0].items()}
    poison = DataBatch(
        data=[mx.nd.array(np.full((8, 10), np.nan, np.float32))],
        label=batch.label)
    stepper.step(poison)
    stepper.sync_to_module()
    after = mod.get_params()[0]
    for n in before:
        assert np.array_equal(before[n], after[n].asnumpy()), n
    ls = stepper._fused.loss_scale_stats()
    assert ls["scale"] == 2.0 ** 14 and ls["finite_streak"] == 0
    stepper.step(batch)      # recovery: a finite step applies again
    ls2 = stepper._fused.loss_scale_stats()
    assert ls2["finite_streak"] == 1
    stepper.sync_to_module()
    recovered = mod.get_params()[0]
    assert not np.array_equal(before["fc1_weight"],
                              recovered["fc1_weight"].asnumpy())


def test_gluon_loss_scale_skip_and_schedule():
    from mxnet_tpu import autograd, gluon
    net = gluon.nn.Sequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(8, activation="relu"))
        net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, loss_scale=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(8, 10).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))
    with autograd.record():
        loss = loss_fn(net(x), y) * tr.loss_scale.scale
    loss.backward()
    tr.step(8)
    assert tr.loss_scale.steps_skipped == 0
    with autograd.record():
        out = net(mx.nd.array(np.full((8, 10), np.nan, np.float32)))
        loss = loss_fn(out, y) * tr.loss_scale.scale
    loss.backward()
    before = {k: p.data().asnumpy().copy()
              for k, p in net.collect_params().items()}
    tr.step(8)
    for k, p in net.collect_params().items():
        assert np.array_equal(before[k], p.data().asnumpy()), k
    assert tr.loss_scale.steps_skipped == 1
    assert tr.loss_scale.scale == 2.0 ** 14


def test_gluon_loss_scale_needs_functional_rule():
    from mxnet_tpu import gluon
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    with pytest.raises(MXNetError, match="functional update rule"):
        gluon.Trainer(net.collect_params(), "adagrad", {},
                      loss_scale=True)


def test_bf16_fp32_default_unaffected(monkeypatch):
    """Without the mode, nothing changes: no guard, no cast."""
    from mxnet_tpu import perf
    monkeypatch.delenv("MXTPU_PRECISION", raising=False)
    mod = _mlp_train_module()
    stepper = perf.module_stepper(mod)
    stepper.step(_train_batch())
    assert stepper._fused.loss_scale_stats() is None
    assert stepper._fused.compute_dtype is None


# ---------------------------------------------------------------------------
# ZeRO + bf16 compose
# ---------------------------------------------------------------------------

def test_zero_bf16_compose_bitwise_vs_replicated(monkeypatch):
    """The ZeRO=1 bitwise contract (PR 9) must survive the bf16 mode:
    sharded-update training under MXTPU_PRECISION=bf16 reproduces the
    replicated bf16 run bit-for-bit, loss-scale guard armed in both."""
    from mxnet_tpu.parallel import SPMDTrainer, make_mesh
    monkeypatch.setenv("MXTPU_PRECISION", "bf16")
    feeds = [{"data": np.random.RandomState(i).rand(16, 12)
              .astype(np.float32),
              "softmax_label": np.random.RandomState(100 + i)
              .randint(0, 4, (16,)).astype(np.float32)}
             for i in range(3)]
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                               name="softmax")
    outs = {}
    for shard in (False, True):
        mesh = make_mesh({"data": 8})
        tr = SPMDTrainer(net, optimizer="sgd",
                         optimizer_params=dict(learning_rate=0.5,
                                               momentum=0.9,
                                               rescale_grad=1.0 / 16),
                         mesh=mesh, shard_optimizer_state=shard)
        mx.random.seed(42)
        tr.bind(data_shapes={"data": (16, 12)},
                label_shapes={"softmax_label": (16,)})
        assert tr.loss_scale_stats() is not None   # mode armed the guard
        for f in feeds:
            tr.step(f)
        assert tr.loss_scale_stats()["finite_streak"] == 3
        arg, _ = tr.get_params()
        outs[shard] = {n: v.asnumpy() for n, v in arg.items()}
    for n in outs[False]:
        assert np.array_equal(outs[True][n], outs[False][n]), n
