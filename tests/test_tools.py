"""Tools tier: parse_log, kill_jobs, caffe prototxt converter, coreml gate,
legacy symbol-JSON loading.

Reference analogues: tools/{parse_log.py,kill-mxnet.py,caffe_converter,
coreml}, src/nnvm/legacy_json_util.cc (LoadLegacyJSON).
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Train-accuracy=0.52\n"
        "INFO Epoch[0] Time cost=3.14\n"
        "INFO Epoch[0] Validation-accuracy=0.49\n"
        "INFO Epoch[1] Train-accuracy=0.81\n"
        "INFO Epoch[1] Time cost=3.02\n"
        "INFO Epoch[1] Validation-accuracy=0.78\n")
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"),
         str(log)], capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert "| 0 | 0.52 | 0.49 | 3.14 |" in res.stdout
    assert "| 1 | 0.81 | 0.78 | 3.02 |" in res.stdout


LENET_PROTOTXT = """
name: "LeNet"
input: "data"
input_dim: 1
input_dim: 1
input_dim: 28
input_dim: 28
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 20 kernel_size: 5 stride: 1 }
}
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer { name: "relu1" type: "ReLU" bottom: "pool1" top: "pool1" }
layer {
  name: "ip1"
  type: "InnerProduct"
  bottom: "pool1"
  top: "ip1"
  inner_product_param { num_output: 10 }
}
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""


def test_caffe_converter_lenet(tmp_path):
    conv = _load(os.path.join(ROOT, "tools", "caffe_converter",
                              "convert_symbol.py"), "convert_symbol")
    proto = tmp_path / "lenet.prototxt"
    proto.write_text(LENET_PROTOTXT)
    sym, input_name, input_dim = conv.convert_symbol(str(proto))
    assert input_name == "data"
    assert input_dim == [1, 1, 28, 28]
    ex = sym.simple_bind(mx.cpu(), data=(1, 1, 28, 28), prob_label=(1,))
    rng = np.random.RandomState(0)
    for n, a in ex.arg_dict.items():
        a[:] = mx.nd.array(rng.normal(0, 0.1, a.shape).astype(np.float32))
    out = ex.forward()[0].asnumpy()
    assert out.shape == (1, 10)
    np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-5)


def test_caffe_converter_unsupported_layer(tmp_path):
    conv = _load(os.path.join(ROOT, "tools", "caffe_converter",
                              "convert_symbol.py"), "convert_symbol")
    proto = tmp_path / "bad.prototxt"
    proto.write_text("""
input: "data"
input_dim: 1
input_dim: 3
layer { name: "x" type: "SPP" bottom: "data" top: "x" }
""")
    with pytest.raises(ValueError, match="SPP"):
        conv.convert_symbol(str(proto))


def test_caffe_converter_model_weights_gated(tmp_path):
    conv = _load(os.path.join(ROOT, "tools", "caffe_converter",
                              "convert_symbol.py"), "convert_symbol")
    with pytest.raises(NotImplementedError, match="caffe"):
        conv.convert_model("a.prototxt", "b.caffemodel")


def test_coreml_converter_gated(tmp_path):
    coreml = _load(os.path.join(ROOT, "tools", "coreml", "converter.py"),
                   "coreml_converter")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2),
        name="softmax")
    args = {n: mx.nd.ones(s) for n, s in zip(
        net.list_arguments(),
        net.infer_shape(data=(1, 4), softmax_label=(1,))[0])
        if n not in ("data", "softmax_label")}
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 0, net, args, {})
    with pytest.raises(NotImplementedError, match="coremltools"):
        coreml.convert(prefix, 0, str(tmp_path / "out.mlmodel"))


def test_legacy_json_loads_and_runs():
    legacy = {
        "nodes": [
            {"op": "null", "param": {}, "name": "data", "inputs": [],
             "backward_source_id": -1, "attr": {"ctx_group": "stage1"}},
            {"op": "null", "param": {}, "name": "fc1_weight", "inputs": [],
             "backward_source_id": -1},
            {"op": "null", "param": {}, "name": "fc1_bias", "inputs": [],
             "backward_source_id": -1},
            {"op": "FullyConnected",
             "param": {"no_bias": "False", "num_hidden": "8"},
             "name": "fc1", "inputs": [[0, 0], [1, 0], [2, 0]],
             "backward_source_id": -1},
            {"op": "Activation", "param": {"act_type": "relu"},
             "name": "relu1", "inputs": [[3, 0]],
             "backward_source_id": -1},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[4, 0]],
    }
    sym = mx.sym.load_json(json.dumps(legacy))
    assert sym.list_arguments() == ["data", "fc1_weight", "fc1_bias"]
    # legacy user attrs survive into attr_dict
    assert sym.attr_dict().get("data", {}).get("ctx_group") == "stage1"
    ex = sym.simple_bind(mx.cpu(), data=(2, 4))
    for n, a in ex.arg_dict.items():
        a[:] = mx.nd.ones(a.shape)
    out = ex.forward()[0]
    assert out.shape == (2, 8)


def test_kill_jobs_no_match():
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "kill_jobs.py"),
         "definitely-not-a-running-process-pattern-xyz"],
        capture_output=True, text=True)
    assert res.returncode == 0
    assert "no processes" in res.stdout


def test_accnn_fc_decomposition(tmp_path):
    # reference tools/accnn/acc_fc.py: SVD split preserves outputs at
    # full rank and approximates them at reduced rank with fewer FLOPs
    accnn = _load(os.path.join(ROOT, "tools", "accnn", "acc_fc.py"),
                  "acc_fc")
    rng = np.random.RandomState(0)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=32,
                                      name="fc1"),
                act_type="relu"),
            num_hidden=8, name="fc2"),
        name="softmax")
    shapes = net.infer_shape(data=(4, 16), softmax_label=(4,))[0]
    args = {n: mx.nd.array(rng.normal(0, 0.5, s).astype(np.float32))
            for n, s in zip(net.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}

    x = rng.rand(4, 16).astype(np.float32)

    def run(sym, params):
        ex = sym.simple_bind(mx.cpu(), grad_req="null", data=(4, 16),
                             softmax_label=(4,))
        ex.copy_params_from(params)
        ex.arg_dict["data"][:] = mx.nd.array(x)
        return ex.forward(is_train=False)[0].asnumpy()

    base = run(net, args)

    # full rank: numerically identical outputs
    sym_full, args_full = accnn.fc_decomposition(net, args, "fc1", 32)
    assert "fc1_weight" not in sym_full.list_arguments()
    assert "fc1_red_weight" in sym_full.list_arguments()
    np.testing.assert_allclose(run(sym_full, args_full), base, rtol=1e-4,
                               atol=1e-5)

    # reduced rank: close outputs
    sym_lr, args_lr = accnn.fc_decomposition(net, args, "fc1", 12)
    assert args_lr["fc1_red_weight"].shape == (12, 16)
    assert args_lr["fc1_rec_weight"].shape == (32, 12)
    np.testing.assert_allclose(run(sym_lr, args_lr), base, atol=0.15)

    # checkpoint round trip through the CLI-facing API
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 0, net, args, {})
    sym2, arg2, _ = mx.model.load_checkpoint(prefix, 0)
    sym_d, args_d = accnn.fc_decomposition(sym2, arg2, "fc2", 8)
    np.testing.assert_allclose(run(sym_d, args_d), base, rtol=1e-4,
                               atol=1e-5)


def test_caffe_converter_lowercase_booleans(tmp_path):
    conv = _load(os.path.join(ROOT, "tools", "caffe_converter",
                              "convert_symbol.py"), "convert_symbol")
    parsed = conv.parse_prototxt(conv._quote_enums("""
convolution_param { num_output: 20 kernel_size: 3 bias_term: false }
pooling_param { pool: MAX global_pooling: true }
"""))
    assert parsed["convolution_param"]["bias_term"] == "false"
    assert parsed["pooling_param"]["global_pooling"] == "true"

    proto = tmp_path / "nb.prototxt"
    proto.write_text("""
input: "data"
input_dim: 1
input_dim: 3
input_dim: 8
input_dim: 8
layer {
  name: "c"
  type: "Convolution"
  bottom: "data"
  top: "c"
  convolution_param { num_output: 4 kernel_size: 3 bias_term: false }
}
layer {
  name: "gp"
  type: "Pooling"
  bottom: "c"
  top: "gp"
  pooling_param { pool: AVE global_pooling: true }
}
""")
    sym, _, _ = conv.convert_symbol(str(proto))
    args = sym.list_arguments()
    assert "c_bias" not in args          # bias_term: false honored
    ex = sym.simple_bind(mx.cpu(), data=(1, 3, 8, 8))
    ex.arg_dict["c_weight"][:] = mx.nd.ones((4, 3, 3, 3))
    ex.arg_dict["data"][:] = mx.nd.ones((1, 3, 8, 8))
    out = ex.forward()[0]
    assert out.shape[2:] == (1, 1)       # global pooling honored


def test_accnn_conv_vh_decomposition():
    # reference tools/accnn/acc_conv.py: full-rank V-H split preserves the
    # conv exactly; reduced rank approximates it
    accnn = _load(os.path.join(ROOT, "tools", "accnn", "acc_conv.py"),
                  "acc_conv")
    rng = np.random.RandomState(0)
    net = mx.sym.SoftmaxOutput(
        mx.sym.Flatten(mx.sym.Activation(
            mx.sym.Convolution(mx.sym.var("data"), num_filter=6,
                               kernel=(3, 3), pad=(1, 1), name="conv1"),
            act_type="relu")),
        name="softmax")
    shapes = net.infer_shape(data=(2, 3, 8, 8), softmax_label=(2,))[0]
    args = {n: mx.nd.array(rng.normal(0, 0.3, s).astype(np.float32))
            for n, s in zip(net.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    x = rng.rand(2, 3, 8, 8).astype(np.float32)

    def run(sym, params):
        ex = sym.simple_bind(mx.cpu(), grad_req="null", data=(2, 3, 8, 8),
                             softmax_label=(2,))
        ex.copy_params_from(params)
        ex.arg_dict["data"][:] = mx.nd.array(x)
        return ex.forward(is_train=False)[0].asnumpy()

    base = run(net, args)
    # full rank (min(C*ky, N*kx) = 9): exact
    sym_f, args_f = accnn.conv_vh_decomposition(net, args, "conv1", 9)
    assert "conv1_v_weight" in sym_f.list_arguments()
    assert "conv1_weight" not in sym_f.list_arguments()
    np.testing.assert_allclose(run(sym_f, args_f), base, rtol=1e-4,
                               atol=1e-5)
    # reduced rank: still close on a smooth input
    sym_r, args_r = accnn.conv_vh_decomposition(net, args, "conv1", 5)
    assert args_r["conv1_v_weight"].shape == (5, 3, 3, 1)
    assert args_r["conv1_h_weight"].shape == (6, 5, 1, 3)
    np.testing.assert_allclose(run(sym_r, args_r), base, atol=0.2)
