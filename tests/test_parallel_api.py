"""User-facing parallelism APIs (VERDICT r1 #5).

TP/PP/SP compose through the public surfaces — the ``MultiHeadAttention``
sym/nd op + gluon layer (seq_axis mesh-axis attr), ``SPMDTrainer`` over a
multi-axis mesh, and ``pipeline_from_symbol`` driving the GPipe schedule
from ctx_group stage annotations — with no ``parallel/*`` internals in
user code.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, models
from mxnet_tpu.parallel import (SPMDTrainer, make_mesh, mesh_scope,
                                pipeline_from_symbol)


# the manual-SPMD paths run through parallel/compat.shard_map, which
# adapts to either jax.shard_map (new API) or
# jax.experimental.shard_map (the 0.4.x line) — skip only when a build
# carries neither
from mxnet_tpu.parallel.compat import has_shard_map

requires_shard_map = pytest.mark.skipif(
    not has_shard_map(),
    reason="no shard_map implementation in this jax build")


def _manual_attention(q, k, v, num_heads, causal):
    B, S, E = q.shape
    H, D = num_heads, E // num_heads

    def split(x):
        return x.reshape(B, S, H, D).transpose(0, 2, 1, 3)

    s = np.einsum("bhqd,bhkd->bhqk", split(q), split(k)) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, split(v))
    return out.transpose(0, 2, 1, 3).reshape(B, S, E)


def test_mha_op_matches_manual():
    rng = np.random.RandomState(0)
    q, k, v = (rng.randn(2, 16, 32).astype(np.float32) for _ in range(3))
    for causal in (False, True):
        out = mx.nd.MultiHeadAttention(
            mx.nd.array(q), mx.nd.array(k), mx.nd.array(v),
            num_heads=4, causal=causal).asnumpy()
        np.testing.assert_allclose(
            out, _manual_attention(q, k, v, 4, causal),
            rtol=1e-4, atol=1e-5)


@requires_shard_map
@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_mha_op_sequence_parallel_matches_full(mode):
    rng = np.random.RandomState(1)
    q, k, v = (rng.randn(2, 16, 32).astype(np.float32) for _ in range(3))
    args = [mx.nd.array(a) for a in (q, k, v)]
    ref = mx.nd.MultiHeadAttention(*args, num_heads=4, causal=True).asnumpy()
    mesh = make_mesh({"data": 2, "seq": 4})
    with mesh_scope(mesh):
        out = mx.nd.MultiHeadAttention(
            *args, num_heads=4, causal=True, seq_axis="seq",
            seq_mode=mode).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


@requires_shard_map
def test_gluon_mha_layer_mesh_transparent():
    rng = np.random.RandomState(2)
    x = mx.nd.array(rng.randn(2, 16, 32).astype(np.float32))
    attn = gluon.nn.MultiHeadAttention(32, 4, causal=True, seq_axis="seq")
    attn.collect_params().initialize(mx.init.Xavier())
    ref = attn(x).asnumpy()
    mesh = make_mesh({"data": 2, "seq": 4})
    with mesh_scope(mesh):
        out = attn(x).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    attn.hybridize()
    with mesh_scope(mesh):
        out_h = attn(x).asnumpy()
    np.testing.assert_allclose(out_h, ref, rtol=1e-4, atol=1e-5)


@requires_shard_map
def test_transformer_lm_4d_training_converges():
    """dp=2 x tp=2 x sp=2 + ZeRO optimizer sharding, all via public API."""
    B, S, V = 8, 16, 64
    mesh = make_mesh({"data": 2, "model": 2, "seq": 2})
    sym = models.get_symbol("transformer_lm", vocab_size=V, seq_len=S,
                            num_layers=2, num_heads=4, d_model=32,
                            seq_axis="seq", seq_mode="ring")
    tr = SPMDTrainer(sym, optimizer="adam",
                     optimizer_params=dict(learning_rate=3e-3,
                                           rescale_grad=1.0 / (B * S)),
                     mesh=mesh, shard_optimizer_state=True)
    tr.bind(data_shapes={"data": (B, S)},
            label_shapes={"softmax_label": (B, S)})
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (B, S + 1))
    feed = {"data": toks[:, :-1].astype(np.float32),
            "softmax_label": toks[:, 1:].astype(np.float32)}
    lab = toks[:, 1:]

    def nll():
        p = np.asarray(tr.step(feed)[0])
        return -np.log(p[np.arange(B)[:, None], np.arange(S)[None, :],
                         lab] + 1e-9).mean()

    l0 = nll()
    for _ in range(40):
        tr.step(feed)
    assert nll() < l0 * 0.5
    # tp actually sharded the FFN weight over 'model'
    spec = tr.params["l0_ffn1_weight"].sharding.spec
    assert "model" in tuple(spec)
    # sp actually sharded the token input over 'seq' (dim 1)
    assert tuple(tr._in_shardings["data"].spec) == ("data", "seq")


def _staged_mlp(n_stages, d):
    data = mx.sym.var("data")
    h = data
    for i in range(n_stages):
        with mx.AttrScope(ctx_group=f"stage{i}"):
            h = mx.sym.FullyConnected(h, name=f"fc{i}", num_hidden=d,
                                      flatten=False)
            h = mx.sym.Activation(h, act_type="tanh", name=f"act{i}")
    return h


@requires_shard_map
def test_pipeline_from_symbol_matches_executor():
    d, n = 16, 4
    sym = _staged_mlp(n, d)
    mesh = make_mesh({"pipe": n}, devices=jax.devices()[:n])
    apply_fn = pipeline_from_symbol(sym, mesh, n_microbatches=n)
    rng = np.random.RandomState(0)
    args = {}
    for i in range(n):
        args[f"fc{i}_weight"] = jnp.asarray(
            rng.normal(0, .4, (d, d)).astype(np.float32))
        args[f"fc{i}_bias"] = jnp.asarray(
            rng.normal(0, .1, (d,)).astype(np.float32))
    x = jnp.asarray(rng.normal(0, 1, (8, d)).astype(np.float32))
    out_pipe = np.asarray(apply_fn(args, x))

    ex = sym.simple_bind(mx.cpu(), data=(8, d), grad_req="null")
    for name, v in args.items():
        ex.arg_dict[name][:] = mx.nd.array(np.asarray(v))
    out_ref = ex.forward(is_train=False, data=np.asarray(x))[0].asnumpy()
    np.testing.assert_allclose(out_pipe, out_ref, rtol=1e-4, atol=1e-5)

    # differentiable end-to-end: train the pipelined model a few steps
    y = jnp.asarray(rng.normal(0, 1, (8, d)).astype(np.float32))

    @jax.jit
    def loss(args, x, y):
        return jnp.mean((apply_fn(args, x) - y) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss))
    l0, _ = grad_fn(args, x, y)
    for _ in range(60):
        l, g = grad_fn(args, x, y)
        args = jax.tree.map(lambda p, gi: p - 0.2 * gi, args, g)
    l1, _ = grad_fn(args, x, y)
    assert float(l1) < float(l0) * 0.5


@requires_shard_map
def test_pipeline_from_symbol_ragged_delegates_to_hetero():
    """Non-isomorphic stages used to be rejected; they now route to the
    heterogeneous flat-buffer pipeline and produce executor-exact
    forwards."""
    d = 16
    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    data = mx.sym.var("data")
    h = data
    for i, hid in enumerate([d, d, 2 * d, d]):
        with mx.AttrScope(ctx_group=f"stage{i}"):
            h = mx.sym.FullyConnected(h, name=f"fc{i}", num_hidden=hid,
                                      flatten=False)
    apply_fn = pipeline_from_symbol(h, mesh, n_microbatches=4)
    assert hasattr(apply_fn, "reference_step")  # hetero path marker
    rng = np.random.RandomState(3)
    args = {}
    pv = d
    for i, hid in enumerate([d, d, 2 * d, d]):
        args[f"fc{i}_weight"] = jnp.asarray(
            rng.normal(0, .4, (hid, pv)).astype(np.float32))
        args[f"fc{i}_bias"] = jnp.asarray(
            rng.normal(0, .1, (hid,)).astype(np.float32))
        pv = hid
    x = jnp.asarray(rng.normal(0, 1, (8, d)).astype(np.float32))
    out_pipe = np.asarray(apply_fn(args, x))
    ex = h.simple_bind(mx.cpu(), data=(8, d), grad_req="null")
    for name, v in args.items():
        ex.arg_dict[name][:] = mx.nd.array(np.asarray(v))
    ref = ex.forward(is_train=False, data=np.asarray(x))[0].asnumpy()
    np.testing.assert_allclose(out_pipe, ref, rtol=1e-4, atol=1e-5)


def test_pipeline_from_symbol_rejects_bad_graphs():
    d = 16
    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    # missing stage annotations entirely
    plain = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=d,
                                  name="fc", flatten=False)
    with pytest.raises(mx.MXNetError):
        pipeline_from_symbol(plain, mesh)


@requires_shard_map
def test_executor_retraces_on_mesh_change():
    """ADVICE r2: the executor's compiled program is keyed on the ambient
    mesh. A graph first run OUTSIDE mesh_scope must not keep running the
    unsharded program when later invoked under a mesh (and vice versa)."""
    import mxnet_tpu.parallel.sequence as seq_mod

    q = mx.sym.var("q")
    out = mx.sym.MultiHeadAttention(q, q, q, num_heads=4, causal=True,
                                    seq_axis="seq", name="attn")
    ex = out.simple_bind(mx.cpu(), q=(2, 16, 32), grad_req="null")
    rng = np.random.RandomState(4)
    x = rng.randn(2, 16, 32).astype(np.float32)

    calls = []
    orig = seq_mod.sequence_sharded_attention

    def counting(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    seq_mod.sequence_sharded_attention = counting
    try:
        ref = ex.forward(is_train=False, q=x)[0].asnumpy()   # no mesh
        assert not calls
        mesh = make_mesh({"data": 2, "seq": 4})
        with mesh_scope(mesh):
            sharded = ex.forward(is_train=False, q=x)[0].asnumpy()
        assert calls, "mesh_scope did not force a retrace onto the " \
                      "sequence-parallel path"
        np.testing.assert_allclose(sharded, ref, rtol=1e-4, atol=1e-5)
        # and back out of the mesh: cached unsharded program, same result
        n = len(calls)
        again = ex.forward(is_train=False, q=x)[0].asnumpy()
        assert len(calls) == n
        np.testing.assert_allclose(again, ref, rtol=1e-4, atol=1e-5)
    finally:
        seq_mod.sequence_sharded_attention = orig


def _pipelined_lm_symbol(V, D, n_stages):
    """Embedding (prologue) -> n isomorphic FC+tanh blocks (pipelined)
    -> head FC + SoftmaxOutput (epilogue): the real-model shape VERDICT
    r2 #4 asked for."""
    data = mx.sym.var("data")
    with mx.AttrScope(ctx_group="prologue"):
        emb_w = mx.sym.var("emb_weight")
        h = mx.sym.Embedding(data, emb_w, input_dim=V, output_dim=D,
                             name="emb")
    for i in range(n_stages):
        with mx.AttrScope(ctx_group=f"stage{i}"):
            h = mx.sym.FullyConnected(h, name=f"blk{i}_fc", num_hidden=D,
                                      flatten=False)
            h = mx.sym.Activation(h, act_type="tanh", name=f"blk{i}_act")
    with mx.AttrScope(ctx_group="epilogue"):
        logits = mx.sym.FullyConnected(h, name="head", num_hidden=V,
                                       flatten=False)
        out = mx.sym.SoftmaxOutput(logits, name="softmax")
    return out


@requires_shard_map
def test_pipeline_heterogeneous_model_1f1b_trains():
    """Embedding->blocks->head pipelines (prologue/epilogue outside the
    isomorphic body) and the 1F1B train_step converges; gradients match
    the non-pipelined executor."""
    V, D, S, B, n = 32, 16, 8, 16, 4
    sym = _pipelined_lm_symbol(V, D, n)
    mesh = make_mesh({"pipe": n}, devices=jax.devices()[:n])
    pipe = pipeline_from_symbol(sym, mesh, n_microbatches=8)
    assert pipe.prologue_param_names == ["emb_weight"]
    assert pipe.epilogue_param_names == ["head_weight", "head_bias"]

    rng = np.random.RandomState(0)
    args = {"emb_weight": jnp.asarray(
        rng.normal(0, .5, (V, D)).astype(np.float32))}
    for i in range(n):
        args[f"blk{i}_fc_weight"] = jnp.asarray(
            rng.normal(0, .3, (D, D)).astype(np.float32))
        args[f"blk{i}_fc_bias"] = jnp.zeros((D,), np.float32)
    args["head_weight"] = jnp.asarray(
        rng.normal(0, .3, (V, D)).astype(np.float32))
    args["head_bias"] = jnp.zeros((V,), np.float32)

    toks = rng.randint(0, V, (B, S + 1))
    x = jnp.asarray(toks[:, :-1].astype(np.float32))
    y = jnp.asarray(toks[:, 1:].astype(np.float32))

    # grads match direct (non-pipelined) autodiff of the same model
    def direct_loss(a, xv, yv):
        e = jnp.take(a["emb_weight"], xv.astype(jnp.int32), axis=0)
        h = e
        for i in range(n):
            h = jnp.tanh(h @ a[f"blk{i}_fc_weight"].T
                         + a[f"blk{i}_fc_bias"])
        logits = h @ a["head_weight"].T + a["head_bias"]
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(
            logp, yv.astype(jnp.int32)[..., None], -1))

    step = jax.jit(pipe.train_step)
    loss0, grads, _ = step(args, x, y)
    ref_loss, ref_g = jax.value_and_grad(direct_loss)(args, x, y)
    np.testing.assert_allclose(float(loss0), float(ref_loss), rtol=1e-5)
    for name in args:
        np.testing.assert_allclose(np.asarray(grads[name]),
                                   np.asarray(ref_g[name]),
                                   rtol=1e-3, atol=1e-6)

    # 1F1B training converges (memorize the toy token stream)
    lr = 1.0
    for _ in range(250):
        loss, grads, _ = step(args, x, y)
        args = {k: v - lr * grads[k] for k, v in args.items()}
    final, _, _ = step(args, x, y)
    assert float(final) < float(loss0) * 0.5, (float(loss0), float(final))

    # inference path (prologue -> GPipe -> epilogue) agrees with the
    # plain executor running the same symbol
    ex = sym.simple_bind(mx.cpu(), data=(B, S), softmax_label=(B, S),
                         grad_req="null")
    probs = np.asarray(pipe(args, x))
    for name, v in args.items():
        ex.arg_dict[name][:] = mx.nd.array(np.asarray(v))
    ref_probs = ex.forward(is_train=False, data=np.asarray(x),
                           softmax_label=np.asarray(y))[0].asnumpy()
    np.testing.assert_allclose(probs, ref_probs, rtol=1e-3, atol=1e-5)
